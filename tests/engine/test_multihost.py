"""Multi-host SPMD scaffold: two controller processes, one global mesh.

Reference boundary: the multi-node executors
(v1/executor/multiproc_executor.py:42, ray_distributed_executor.py) with
their StatelessProcessGroup bootstrap (distributed/utils.py:138). JAX
analogue validated here: each host process calls
``jax.distributed.initialize`` (the worker does it from
ParallelConfig.num_hosts/host_rank/coordinator_address), after which
``jax.devices()`` spans both processes and one engine step executes SPMD
across them — the same multi-controller layout a v5e pod uses, here with
2 processes x 4 virtual CPU devices.
"""

import functools
import os
import subprocess
import sys

import pytest

from vllm_distributed_tpu.utils import get_open_port

# Capability probe: some jax builds cannot run multi-controller
# computations on the CPU backend at all ("Multiprocess computations
# aren't implemented on the CPU backend" during the cross-process
# device_put model load). Probing once with a minimal 2-process
# sharded computation keeps tier-1 signal clean on such containers —
# a known-red environment skips instead of failing every SPMD test.
_PROBE = r"""
import os, sys
rank = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=rank)
from jax.sharding import Mesh, NamedSharding, PartitionSpec
mesh = Mesh(np.array(jax.devices()).reshape(-1), ("x",))
x = jax.device_put(jnp.arange(8.0),
                   NamedSharding(mesh, PartitionSpec("x")))
y = jax.jit(lambda a: a + 1, out_shardings=NamedSharding(
    mesh, PartitionSpec()))(x)
np.asarray(jax.device_get(y))
print("PROBE-OK", flush=True)
"""


@functools.lru_cache(maxsize=1)
def _multiprocess_cpu_supported() -> bool:
    import time
    port = get_open_port()
    procs = [
        subprocess.Popen([sys.executable, "-c", _PROBE, str(rank),
                          str(port)],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for rank in range(2)
    ]
    # One SHARED deadline, not per-process: a warning-then-silent jax
    # init hang (the documented bench-probe failure mode) must cost the
    # tier-1 budget at most ~2 minutes total, not 2 x 3 minutes.
    deadline = time.monotonic() + 120
    ok = True
    for p in procs:
        try:
            out, _ = p.communicate(
                timeout=max(5.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            return False
        ok = ok and p.returncode == 0 and "PROBE-OK" in out
    return ok


def _require_multiprocess_cpu() -> None:
    if not _multiprocess_cpu_supported():
        pytest.skip("jax multiprocess computations unavailable on this "
                    "container's CPU backend")

_CHILD = r"""
import os, sys
rank = int(sys.argv[1]); port = sys.argv[2]
n_hosts = int(sys.argv[3]); dev_per_host = int(sys.argv[4])
tp = int(sys.argv[5]); pp = int(sys.argv[6])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    f"--xla_force_host_platform_device_count={dev_per_host}")
os.environ["VDT_PALLAS_INTERPRET"] = "1"
os.environ["VDT_PLATFORM"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                         LoadConfig, ModelConfig,
                                         ParallelConfig, SchedulerConfig)
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams
from transformers import LlamaConfig

config = EngineConfig(
    model_config=ModelConfig(
        model="dummy-multihost", dtype="float32", max_model_len=64,
        skip_tokenizer_init=True,
        hf_overrides=dict(vocab_size=128, hidden_size=64,
                          intermediate_size=128, num_hidden_layers=2,
                          num_attention_heads=8, num_key_value_heads=8,
                          max_position_embeddings=64,
                          architectures=["LlamaForCausalLM"])),
    cache_config=CacheConfig(block_size=4, num_gpu_blocks=64),
    scheduler_config=SchedulerConfig(max_num_batched_tokens=64,
                                     max_num_seqs=8, max_model_len=64),
    load_config=LoadConfig(load_format="dummy"),
    parallel_config=ParallelConfig(
        tensor_parallel_size=tp, pipeline_parallel_size=pp,
        num_hosts=n_hosts, host_rank=rank,
        coordinator_address=f"127.0.0.1:{port}"),
)
config.model_config.hf_config = LlamaConfig(**config.model_config.hf_overrides)

# Multi-controller SPMD: every host runs the identical engine program on
# the identical request stream; collectives tie the step together.
engine = LLMEngine(config, load_tokenizer=False)
assert jax.process_count() == n_hosts, jax.process_count()
assert len(jax.devices()) == n_hosts * dev_per_host, jax.devices()

sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)
engine.add_request("mh-0", [3, 17, 92, 45, 8], sp)
engine.add_request("mh-1", [5, 9, 33, 71], sp)
done = {}
for _ in range(100):
    for out in engine.step():
        if out.finished:
            done[out.request_id] = out.outputs[0].token_ids
    if len(done) == 2:
        break
print("RESULT", rank, sorted(done.items()), flush=True)
"""


_DRIVER = r"""
import os, sys
rank = int(sys.argv[1]); port = sys.argv[2]; baddr = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["VDT_PALLAS_INTERPRET"] = "1"
os.environ["VDT_PLATFORM"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                         LoadConfig, ModelConfig,
                                         ParallelConfig, SchedulerConfig)
from transformers import LlamaConfig

def make_config(rank, port, baddr):
    config = EngineConfig(
        model_config=ModelConfig(
            model="dummy-mh-exec", dtype="float32", max_model_len=64,
            skip_tokenizer_init=True,
            hf_overrides=dict(vocab_size=128, hidden_size=64,
                              intermediate_size=128, num_hidden_layers=2,
                              num_attention_heads=8, num_key_value_heads=8,
                              max_position_embeddings=64,
                              architectures=["LlamaForCausalLM"])),
        cache_config=CacheConfig(block_size=4, num_gpu_blocks=64,
                                 num_gpu_blocks_override=64),
        scheduler_config=SchedulerConfig(max_num_batched_tokens=64,
                                         max_num_seqs=8, max_model_len=64),
        load_config=LoadConfig(load_format="dummy"),
        parallel_config=ParallelConfig(
            tensor_parallel_size=8, num_hosts=2, host_rank=rank,
            coordinator_address=f"127.0.0.1:{port}",
            broadcast_addr=baddr),
    )
    config.model_config.hf_config = LlamaConfig(
        **config.model_config.hf_overrides)
    return config

config = make_config(rank, port, baddr)
if rank == 0:
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    engine = LLMEngine(config, load_tokenizer=False)
    from vllm_distributed_tpu.executor.multihost import MultiHostExecutor
    assert isinstance(engine.engine_core.engine_core.executor,
                      MultiHostExecutor)
    sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)
    engine.add_request("mh-0", [3, 17, 92, 45, 8], sp)
    engine.add_request("mh-1", [5, 9, 33, 71], sp)
    done = {}
    for _ in range(100):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out.outputs[0].token_ids
        if len(done) == 2:
            break
    print("RESULT", rank, sorted(done.items()), flush=True)
    engine.shutdown()
else:
    from vllm_distributed_tpu.executor.multihost import run_worker_follower
    steps = run_worker_follower(config)
    assert steps >= 2, steps
    print("RESULT", rank, "follower-steps", steps, flush=True)
"""


@pytest.mark.parametrize("transport", ["tcp", "shm"])
def test_scheduler_broadcast_executor(tmp_path, transport):
    """Host 0 schedules + broadcasts; host 1 replays worker steps SPMD
    (the MultiprocExecutor-boundary equivalent). Runs over both the ZMQ
    TCP transport and the native shared-memory ring (shm://)."""
    _require_multiprocess_cpu()
    port, bport = get_open_port(), get_open_port()
    baddr = (f"tcp://127.0.0.1:{bport}" if transport == "tcp"
             else f"shm://vdt_mh_{os.getpid()}_{bport}")
    procs = [
        subprocess.Popen([sys.executable, "-c", _DRIVER, str(rank),
                          str(port), baddr],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
    assert any("RESULT 0" in o for o in outs)
    assert any("follower-steps" in o for o in outs)
    driver_line = [ln for ln in outs[0].splitlines()
                   if ln.startswith("RESULT 0")]
    assert driver_line and "mh-0" in driver_line[0]


def _run_spmd(n_hosts, dev_per_host, tp, pp, timeout=600):
    _require_multiprocess_cpu()
    port = get_open_port()
    procs = [
        subprocess.Popen([sys.executable, "-c", _CHILD, str(rank),
                          str(port), str(n_hosts), str(dev_per_host),
                          str(tp), str(pp)],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for rank in range(n_hosts)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
    results = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT")]
        assert lines, out[-2000:]
        results.append(lines[0].split(" ", 2)[2])
    # Every controller computed the identical step results.
    assert all(r == results[0] for r in results)


def test_two_process_spmd_engine_step(tmp_path):
    _run_spmd(n_hosts=2, dev_per_host=4, tp=8, pp=1)


def test_four_process_tp_lattice(tmp_path):
    """4 controller processes x 2 virtual devices, one TP=8 mesh
    (VERDICT r4 #8: the multihost path beyond 2 processes)."""
    _run_spmd(n_hosts=4, dev_per_host=2, tp=8, pp=1)


def test_four_process_pp_tp_lattice(tmp_path):
    """4 processes, PP=2 stages x TP=4: the staged sub-meshes each span
    two processes, activations hand off across the stage boundary."""
    _run_spmd(n_hosts=4, dev_per_host=2, tp=4, pp=2)
