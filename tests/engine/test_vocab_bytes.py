"""vocab_bytes_from_tokenizer against real HF fast tokenizers.

The grammar byte table must reflect each token's true text contribution:
sentencepiece vocabs strip the leading-space marker on lone-token decode
and byte-level BPE vocabs decode partial-UTF-8 pieces to U+FFFD, so the
table cannot be built from plain per-token decode (ADVICE round 3).
"""

import pytest

from vllm_distributed_tpu.structured_output.manager import (
    vocab_bytes_from_tokenizer)


def _fast(tok, **kw):
    from transformers import PreTrainedTokenizerFast
    return PreTrainedTokenizerFast(tokenizer_object=tok, **kw)


@pytest.fixture(scope="module")
def byte_level_tokenizer():
    """GPT-2/Llama-3-style byte-level BPE: pieces are byte-mapped chars;
    'é' (UTF-8 c3 a9) appears both whole and split across two pieces."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers
    # Byte-level piece strings: space -> 'Ġ', 0xC3 -> 'Ã', 0xA9 -> '©'.
    vocab = {"<unk>": 0, "<eos>": 1, "hello": 2, "Ġworld": 3,
             "Ã©": 4, "Ã": 5, "©": 6, "ĊĊ": 7}
    tok = Tokenizer(models.BPE(vocab=vocab, merges=[], unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    return _fast(tok, unk_token="<unk>", eos_token="<eos>")


@pytest.fixture(scope="module")
def sentencepiece_tokenizer():
    """Llama-2/Mistral-style: pieces carry the U+2581 space marker and
    <0xHH> byte-fallback entries."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers
    pieces = [("<unk>", 0.0), ("</s>", 0.0), ("▁hello", -1.0),
              ("▁", -2.0), ("hello", -3.0), ("<0x0A>", -4.0),
              ("▁the", -1.5), ("é", -5.0)]
    tok = Tokenizer(models.Unigram(pieces, unk_id=0, byte_fallback=True))
    tok.pre_tokenizer = pre_tokenizers.Metaspace()
    tok.decoder = decoders.Metaspace()
    return _fast(tok, unk_token="<unk>", eos_token="</s>")


def test_byte_level_pieces_map_to_raw_bytes(byte_level_tokenizer):
    table = vocab_bytes_from_tokenizer(byte_level_tokenizer)
    ids = {t: i for t, i in byte_level_tokenizer.get_vocab().items()}
    assert table[ids["hello"]] == b"hello"
    assert table[ids["Ġworld"]] == b" world"           # space restored
    assert table[ids["Ã©"]] == "é".encode("utf-8")      # c3 a9
    assert table[ids["Ã"]] == b"\xc3"                   # NOT U+FFFD
    assert table[ids["©"]] == b"\xa9"                   # NOT U+FFFD
    assert table[ids["ĊĊ"]] == b"\n\n"
    # Specials contribute nothing.
    assert table[ids["<eos>"]] == b""


def test_partial_utf8_decode_is_lossy_without_piece_mapping(
        byte_level_tokenizer):
    """The failure mode the table derivation must avoid: lone-token
    decode of a continuation-byte piece yields U+FFFD."""
    ids = byte_level_tokenizer.get_vocab()
    s = byte_level_tokenizer.decode([ids["Ã"]])
    assert "�" in s


def test_sentencepiece_marker_and_byte_fallback(sentencepiece_tokenizer):
    table = vocab_bytes_from_tokenizer(sentencepiece_tokenizer)
    ids = sentencepiece_tokenizer.get_vocab()
    assert table[ids["▁hello"]] == b" hello"            # marker -> space
    assert table[ids["▁the"]] == b" the"
    assert table[ids["▁"]] == b" "
    assert table[ids["hello"]] == b"hello"
    assert table[ids["<0x0A>"]] == b"\n"                # byte fallback
    assert table[ids["é"]] == "é".encode("utf-8")
    assert table[ids["</s>"]] == b""


def test_sentencepiece_masks_follow_real_token_text(sentencepiece_tokenizer):
    """End-to-end through the manager: a grammar over ' hello' must allow
    exactly the marker-bearing piece, which lone-token decode misreports."""
    from vllm_distributed_tpu.structured_output.manager import (
        StructuredOutputManager)
    table = vocab_bytes_from_tokenizer(sentencepiece_tokenizer)
    mgr = StructuredOutputManager(table)
    ids = sentencepiece_tokenizer.get_vocab()
    eos = sentencepiece_tokenizer.eos_token_id
    mgr.add_request("r", {"regex": " hello"}, eos_token_id=eos)
    mask = mgr.mask_for("r")
    assert mask[ids["▁hello"]]
    assert not mask[ids["hello"]]
    mgr.advance("r", [ids["▁hello"]])
    mask = mgr.mask_for("r")
    assert mask[eos]
