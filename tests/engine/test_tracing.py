"""Request tracing spans (reference: vllm/tracing.py + tests/tracing/):
one parent span per finished request with latency/usage attributes AND
child phase spans (queue/prefill/decode/...) stitched from the
request-lifecycle timeline, via the built-in JSONL exporter. A request
replayed through an engine restart keeps its original request id and
carries the journal/replay events."""

import asyncio
import json

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import fault_injection as fi


def test_jsonl_tracer_follows_rotation(tmp_path):
    """The persistent handle must not defeat logrotate: a rename out
    from under the tracer redirects the NEXT span to a fresh file at
    the configured path (writes to the renamed inode would succeed, so
    only the inode check can catch this)."""
    import os

    from vllm_distributed_tpu.tracing import JsonlTracer
    path = tmp_path / "spans.jsonl"
    tracer = JsonlTracer(str(path))
    tracer.emit({"req": 1})
    os.rename(path, tmp_path / "spans.jsonl.1")
    tracer.emit({"req": 2})
    tracer.shutdown()
    assert len(path.read_text().splitlines()) == 1
    assert len((tmp_path / "spans.jsonl.1")
               .read_text().splitlines()) == 1


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    HFLlama(cfg).eval().save_pretrained(
        tmp_path_factory.mktemp("tiny_llama_tr"), safe_serialization=True)
    return str(tmp_path_factory.getbasetemp() / "tiny_llama_tr0")


def test_spans_written_per_request(checkpoint, tmp_path):
    trace_file = str(tmp_path / "spans.jsonl")
    engine = LLMEngine(EngineArgs(
        model=checkpoint, dtype="float32", block_size=4,
        num_gpu_blocks_override=64, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
        skip_tokenizer_init=True,
        otlp_traces_endpoint=f"file://{trace_file}",
    ).create_engine_config())
    sp = SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True)
    for i in range(3):
        engine.add_request(f"t-{i}", [3 + i, 17, 92, 45], sp)
    for _ in range(200):
        engine.step()
        if not engine.has_unfinished_requests():
            break
    spans = [json.loads(line) for line in open(trace_file)]
    assert len(spans) == 3
    for span in spans:
        a = span["attributes"]
        assert a["gen_ai.usage.completion_tokens"] == 5
        assert a["gen_ai.usage.prompt_tokens"] == 4
        assert a["gen_ai.latency.time_to_first_token"] > 0
        assert a["gen_ai.latency.e2e"] >= \
            a["gen_ai.latency.time_to_first_token"]
        assert a["gen_ai.response.finish_reason"] == "length"
        # Phase child spans under the parent: a plain request shows at
        # least queue -> prefill -> decode, each with a non-negative
        # in-parent offset and duration.
        phases = {p["phase"]: p for p in span["phases"]}
        assert {"queue", "prefill", "decode"} <= set(phases)
        for p in span["phases"]:
            assert p["start_s"] >= 0 and p["duration_s"] >= 0
        assert phases["queue"]["start_s"] <= phases["prefill"]["start_s"]
        assert (phases["prefill"]["start_s"]
                <= phases["decode"]["start_s"])
        # The raw timeline rides along for forensics.
        names = [e[1] for e in span["events"]]
        assert "arrived" in names and "scheduled" in names
        assert "first_token" in names and "finished" in names


# ---------------------------------------------------------------------------
# Crash recovery: the trace survives an engine restart
# ---------------------------------------------------------------------------


@pytest.mark.faults
def test_replayed_request_trace_links_original_id(checkpoint, tmp_path):
    """Kill the core mid-decode (PR2 harness): the journaled request
    replays as a continuation into the respawned core, and the emitted
    trace is ONE parent span under the ORIGINAL request id whose
    timeline carries the engine_death/journal_replay events."""
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    fi.clear()
    trace_file = str(tmp_path / "replay_spans.jsonl")
    engine = AsyncLLM(EngineArgs(
        model=checkpoint, dtype="float32", block_size=4,
        num_gpu_blocks_override=64, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
        skip_tokenizer_init=True,
        restart_backoff_base_s=0.01, restart_backoff_max_s=0.05,
        otlp_traces_endpoint=f"file://{trace_file}",
    ).create_engine_config(), load_tokenizer=False)

    async def run():
        sp = SamplingParams(temperature=0.0, max_tokens=24,
                            ignore_eos=True)
        got_first = False
        final = None
        async for out in engine.generate([3, 17, 92, 45, 8],
                                         sp, request_id="traced-0"):
            if not got_first:
                got_first = True
                fi.inject("engine_core.die", max_fires=1)
            final = out
        assert final is not None and final.finished
        return final.outputs[0].token_ids

    try:
        tokens = asyncio.run(asyncio.wait_for(run(), timeout=180.0))
        assert len(tokens) == 24
        assert engine.output_processor.stats.num_requests_replayed >= 1
        spans = [json.loads(line) for line in open(trace_file)]
        mine = [s for s in spans
                if s["attributes"]["gen_ai.request.id"] == "traced-0"]
        # ONE parent span for the whole request, original id, replay
        # visible on its timeline.
        assert len(mine) == 1
        span = mine[0]
        assert span["attributes"]["gen_ai.usage.completion_tokens"] == 24
        names = [e[1] for e in span["events"]]
        assert "engine_death" in names
        assert "journal_replay" in names
        phase_names = {p["phase"] for p in span["phases"]}
        assert {"queue", "prefill", "decode"} <= phase_names
        # The death -> replay window surfaces as a stall child span.
        assert "stall" in phase_names
    finally:
        fi.clear()
        engine.shutdown()
