"""Request tracing spans (reference: vllm/tracing.py + tests/tracing/):
one span per finished request with latency/usage attributes, via the
built-in JSONL exporter."""

import json

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    HFLlama(cfg).eval().save_pretrained(
        tmp_path_factory.mktemp("tiny_llama_tr"), safe_serialization=True)
    return str(tmp_path_factory.getbasetemp() / "tiny_llama_tr0")


def test_spans_written_per_request(checkpoint, tmp_path):
    trace_file = str(tmp_path / "spans.jsonl")
    engine = LLMEngine(EngineArgs(
        model=checkpoint, dtype="float32", block_size=4,
        num_gpu_blocks_override=64, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
        skip_tokenizer_init=True,
        otlp_traces_endpoint=f"file://{trace_file}",
    ).create_engine_config())
    sp = SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True)
    for i in range(3):
        engine.add_request(f"t-{i}", [3 + i, 17, 92, 45], sp)
    for _ in range(200):
        engine.step()
        if not engine.has_unfinished_requests():
            break
    spans = [json.loads(line) for line in open(trace_file)]
    assert len(spans) == 3
    for span in spans:
        a = span["attributes"]
        assert a["gen_ai.usage.completion_tokens"] == 5
        assert a["gen_ai.usage.prompt_tokens"] == 4
        assert a["gen_ai.latency.time_to_first_token"] > 0
        assert a["gen_ai.latency.e2e"] >= \
            a["gen_ai.latency.time_to_first_token"]
        assert a["gen_ai.response.finish_reason"] == "length"
