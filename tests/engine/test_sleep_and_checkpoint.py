"""Sleep/wake (RLHF colocation) and sharded-state checkpoints (model:
reference tests for EngineCore.sleep/wake_up + save/load_sharded_state
examples)."""

import jax
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_sw")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def run_one(engine, prompt, tag="r"):
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    engine.add_request(tag, prompt, sp)
    for _ in range(100):
        for out in engine.step():
            if out.finished:
                return out.outputs[0].token_ids
    raise AssertionError("did not finish")


PROMPT = [3, 17, 92, 45, 8]


def test_sleep_wake_restores_generation(checkpoint):
    engine = make_engine(checkpoint)
    before = run_one(engine, PROMPT, "a")

    freed = engine.sleep(level=1)
    assert freed > 0
    runner = engine.engine_core.engine_core.executor.worker.model_runner
    assert runner.params is None and runner.kv_caches is None

    engine.wake_up()
    after = run_one(engine, PROMPT, "b")
    assert after == before


def test_sleep_level2_reloads_from_checkpoint(checkpoint):
    engine = make_engine(checkpoint)
    before = run_one(engine, PROMPT, "a")
    engine.sleep(level=2)
    engine.wake_up()
    assert run_one(engine, PROMPT, "b") == before


def test_sleep_rejected_with_inflight_requests(checkpoint):
    engine = make_engine(checkpoint)
    sp = SamplingParams(temperature=0.0, max_tokens=32, ignore_eos=True)
    engine.add_request("busy", PROMPT, sp)
    engine.step()
    with pytest.raises(ValueError):
        engine.sleep()
    # Drain so teardown is clean.
    while engine.has_unfinished_requests():
        engine.step()


def test_sharded_state_round_trip(checkpoint, tmp_path):
    engine = make_engine(checkpoint)
    before = run_one(engine, PROMPT, "a")
    ckpt = str(tmp_path / "sharded")
    engine.engine_core.call_utility("save_sharded_state", ckpt)

    reloaded = make_engine(checkpoint, load_format="sharded_state",
                           sharded_state_path=ckpt)
    assert run_one(reloaded, PROMPT, "b") == before


def test_sharded_state_round_trip_int8_tp2(checkpoint, tmp_path):
    """Quantized + TP-sharded tree: the saved state keeps the int8
    payloads and the reload shards them straight onto the mesh."""
    engine = make_engine(checkpoint, quantization="int8",
                         tensor_parallel_size=2)
    before = run_one(engine, PROMPT, "a")
    ckpt = str(tmp_path / "sharded_q8")
    engine.engine_core.call_utility("save_sharded_state", ckpt)

    reloaded = make_engine(checkpoint, load_format="sharded_state",
                           sharded_state_path=ckpt, quantization="int8",
                           tensor_parallel_size=2)
    runner = reloaded.engine_core.engine_core.executor.worker.model_runner
    dtypes = {str(x.dtype)
              for x in jax.tree_util.tree_leaves(runner.params)}
    assert "int8" in dtypes
    assert run_one(reloaded, PROMPT, "b") == before


def test_sharded_state_round_trip_gpt_oss(tmp_path_factory, tmp_path):
    """Extended param trees (sinks, router bias, per-expert biases)
    survive the orbax save/restore + generalized placement."""
    import transformers

    cfg = transformers.GptOssConfig(
        vocab_size=128, hidden_size=64, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, num_local_experts=4,
        num_experts_per_tok=2, sliding_window=8,
        max_position_embeddings=64, head_dim=16, eos_token_id=1)
    torch.manual_seed(17)
    hf = transformers.GptOssForCausalLM(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_gptoss_ckpt"))
    hf.save_pretrained(path, safe_serialization=True)

    engine = make_engine(path)
    before = run_one(engine, PROMPT, "a")
    ckpt = str(tmp_path / "sharded_oss")
    engine.engine_core.call_utility("save_sharded_state", ckpt)
    reloaded = make_engine(path, load_format="sharded_state",
                           sharded_state_path=ckpt)
    assert run_one(reloaded, PROMPT, "b") == before
