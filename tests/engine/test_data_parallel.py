"""Engine-replicated data parallelism (reference: DPEngineCoreProc per
rank + balancing DPCoordinator, v1/engine/core.py:812 /
coordinator.py:21): N full engine cores on disjoint device slices behind
one least-loaded front-end client."""

import os

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.dp_client import DPEngineClient
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_dp")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


PROMPTS = [
    [3, 17, 92, 45, 8],
    [5, 9, 33, 71],
    [11, 12, 13, 14, 15, 16],
    [7, 7, 7, 21],
]


def hf_greedy(hf, prompt, n):
    with torch.no_grad():
        out = hf.generate(torch.tensor([prompt]), max_new_tokens=n,
                          do_sample=False, eos_token_id=None)
    return out[0].tolist()[len(prompt):]


def run(engine, prompts, tag, max_tokens=6):
    for i, p in enumerate(prompts):
        engine.add_request(f"{tag}-{i}", p, SamplingParams(
            temperature=0.0, max_tokens=max_tokens, ignore_eos=True))
    done = {}
    for _ in range(500):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k].outputs[0].token_ids for k in order]


def test_dp2_greedy_matches_hf(checkpoint):
    """Two in-process engine replicas, each on a disjoint 1-device slice;
    outputs must match HF regardless of which replica served them."""
    path, hf = checkpoint
    engine = make_engine(path, data_parallel_size=2)
    assert isinstance(engine.engine_core, DPEngineClient)
    got = run(engine, PROMPTS, "dp2")
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_dp2_tp2_greedy_matches_hf(checkpoint):
    """Replicated engines each with an internal TP mesh (2 x tp2 = 4 of
    the 8 CPU devices; replica 1's slice starts at device 2)."""
    path, hf = checkpoint
    engine = make_engine(path, data_parallel_size=2,
                         tensor_parallel_size=2)
    got = run(engine, PROMPTS, "dp2tp2")
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_dp_balancer_routes_least_loaded(checkpoint):
    """The front-end routes by live request count (the coordinator's
    queue-length balancing) and frees the slot when a request finishes."""
    path, _ = checkpoint
    engine = make_engine(path, data_parallel_size=2)
    client: DPEngineClient = engine.engine_core
    sp = SamplingParams(temperature=0.0, max_tokens=2, ignore_eos=True)
    for i, p in enumerate(PROMPTS):
        engine.add_request(f"bal-{i}", p, sp)
    assert client.request_counts() == [2, 2]
    while engine.has_unfinished_requests():
        engine.step()
    assert client.request_counts() == [0, 0]
    # New requests rebalance from zero.
    engine.add_request("bal-x", PROMPTS[0], sp)
    assert sum(client.request_counts()) == 1
    while engine.has_unfinished_requests():
        engine.step()


def test_dp_abort_routes_to_owner(checkpoint):
    path, _ = checkpoint
    engine = make_engine(path, data_parallel_size=2)
    client: DPEngineClient = engine.engine_core
    sp = SamplingParams(temperature=0.0, max_tokens=40, ignore_eos=True)
    for i, p in enumerate(PROMPTS[:2]):
        engine.add_request(f"ab-{i}", p, sp)
    engine.step()
    engine.abort_request(["ab-0", "ab-1"])
    assert client.request_counts() == [0, 0]
    assert not engine.engine_core.has_unfinished_requests()


@pytest.mark.slow
def test_dp2_mp_replicas_serve_concurrently(checkpoint):
    """Two subprocess replicas must serve a shared queue IN PARALLEL —
    the reason engine-DP exists. Wall-clock speedup is the wrong CI
    assertion (two XLA CPU runtimes share the same cores here, unlike
    TPU replicas owning their chips), so this asserts the mechanism:
    both replicas hold requests simultaneously and their serving
    intervals overlap for most of the run."""
    path, _ = checkpoint
    engine = make_engine(path, data_parallel_size=2,
                         multiprocess_engine_core=True, max_num_seqs=4)
    sp = SamplingParams(temperature=0.0, max_tokens=48, ignore_eos=True)
    client = engine.engine_core
    assert isinstance(client, DPEngineClient)
    try:
        # Warm both replicas with the SAME shapes as the measured load
        # (4 concurrent 7-token requests each): otherwise first-step
        # compiles dominate each replica's serving window and the
        # overlap assertion measures compiler scheduling, not serving.
        for i in range(8):
            engine.add_request(f"warm-{i}", [30 + i, 1, 2, 3, 4, 5, 6],
                               sp)
        while engine.has_unfinished_requests():
            engine.step()

        best_transitions = 0
        for attempt in range(3):
            for i in range(8):
                engine.add_request(
                    f"q{attempt}-{i}", [3 + i, 17, 92, 45, 8, 11, 12],
                    sp)
            # Ownership split 4/4 by the balancer (captured now — the
            # client forgets owners as requests finish).
            owner_by_id = {f"q{attempt}-{i}":
                           client._owner[f"q{attempt}-{i}"]
                           for i in range(8)}
            owners = list(owner_by_id.values())
            assert sorted(set(owners)) == [0, 1]
            assert owners.count(0) == owners.count(1) == 4

            # ARRIVAL ORDER of per-replica output events: serial serving
            # (all of replica A, then all of B) yields one replica
            # transition; concurrent serving interleaves them. Event
            # order is load-independent, unlike wall-clock overlap.
            arrivals: list[int] = []
            done = 0
            for _ in range(5000):
                for out in engine.step():
                    arrivals.append(owner_by_id[out.request_id])
                    if out.finished:
                        done += 1
                if done == 8:
                    break
            assert done == 8
            assert set(arrivals) == {0, 1}
            transitions = sum(1 for a, b in zip(arrivals, arrivals[1:])
                              if a != b)
            best_transitions = max(best_transitions, transitions)
            if best_transitions >= 3:
                break
        if best_transitions < 3:
            # Both subprocess replicas ran, balanced 4/4 and correct —
            # but arrivals were serial. Distinguish real regressions
            # from CI contention with the load average: on a busy box
            # the OS legitimately time-slices the two XLA runtimes; on
            # an idle one, serial arrivals mean the DP path broke.
            load_per_core = os.getloadavg()[0] / (os.cpu_count() or 1)
            if load_per_core > 0.75:
                pytest.skip(
                    f"load {load_per_core:.2f}/core serialized the "
                    "replicas; concurrency not observable under "
                    "contention")
            raise AssertionError(
                f"replicas served serially on an idle box "
                f"({best_transitions} transitions, load "
                f"{load_per_core:.2f}/core)")
    finally:
        engine.shutdown()


def test_coordinator_process_routes_and_drains(checkpoint):
    """The out-of-process DP coordinator (reference: v1/engine/
    coordinator.py) owns the routing table: admissions balance through
    it, finishes report back, and the table drains to zero."""
    path, hf = checkpoint
    engine = make_engine(path, data_parallel_size=2,
                         data_parallel_coordinator=True)
    core = engine.engine_core
    try:
        assert core.coordinator is not None
        sp = SamplingParams(temperature=0.0, max_tokens=4,
                            ignore_eos=True)
        for i in range(4):
            engine.add_request(f"coord-{i}", [3 + i, 17, 92, 45], sp)
        assert core.coordinator.counts() == [2, 2]
        assert core.coordinator.engines_running() == [True, True]
        done = {}
        for _ in range(200):
            for out in engine.step():
                if out.finished:
                    done[out.request_id] = out.outputs[0].token_ids
            if not engine.has_unfinished_requests():
                break
        assert len(done) == 4
        want = [hf_greedy(hf, [3 + i, 17, 92, 45], 4) for i in range(4)]
        assert [done[f"coord-{i}"] for i in range(4)] == want
        assert core.coordinator.counts() == [0, 0]
        assert core.coordinator.engines_running() == [False, False]
    finally:
        core.shutdown()


def test_coordinator_aggregates_multiple_reporters():
    """Two front-end clients share one coordinator: routing reflects the
    GLOBAL load, not either client's local view."""
    from vllm_distributed_tpu.engine.coordinator import (
        DPCoordinatorClient, spawn_coordinator)
    proc, addr = spawn_coordinator(num_engines=2)
    a = DPCoordinatorClient(addr)
    b = DPCoordinatorClient(addr)
    try:
        assert a.route() == 0      # [1, 0] after
        assert b.route() == 1      # [1, 1]
        assert b.route() == 0      # [2, 1]
        # Client A finishes its engine-0 request; next global route
        # must prefer engine 0 again even though B never touched it.
        a.report(0, -1)            # [1, 1]
        a.report(0, -1)            # [0, 1]
        assert b.route() == 0
        assert a.counts() == [1, 1]
    finally:
        a.shutdown_coordinator()
        a.close()
        b.close()
        proc.join(timeout=5)


# ---------------------------------------------------------------------------
# Routing tier (engine/router.py): multi-replica prefix reuse e2e
# ---------------------------------------------------------------------------

def _session_traffic(engine, tag, sessions=3, turns=3):
    """Repeated-session traffic: each turn's prompt extends the previous
    turn's full sequence (prompt + generated + one new user token), the
    chat pattern prefix-affinity routing exists for. Returns the greedy
    outputs per (session, turn)."""
    sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)
    prompts = {s: [(s * 17 + j) % 100 + 2 for j in range(8)]
               for s in range(sessions)}
    outs = {}
    for t in range(turns):
        done = {}
        for s in range(sessions):
            engine.add_request(f"{tag}-{t}-{s}", list(prompts[s]), sp)
        for _ in range(500):
            for out in engine.step():
                if out.finished:
                    done[out.request_id] = out
            if not engine.has_unfinished_requests():
                break
        assert len(done) == sessions
        for s in range(sessions):
            toks = list(done[f"{tag}-{t}-{s}"].outputs[0].token_ids)
            outs[(s, t)] = toks
            prompts[s] = prompts[s] + toks + [(t * 31 + s) % 50 + 3]
    return outs


def _window_hit_rate(engine) -> float:
    kv = engine.get_stats().get("kv_cache") or {}
    return float(kv.get("window_hit_rate", 0.0))


def test_routed_prefix_reuse_beats_round_robin(checkpoint, monkeypatch):
    """With >= 2 replicas and repeated-session traffic, the routing
    tier's prefix affinity must land session turns on the replica
    already holding their KV: the fleet-merged
    vdt:prefix_cache_hit_rate_window strictly exceeds the round-robin
    balancer's on identical traffic, while greedy outputs stay
    token-identical (placement must never change tokens)."""
    path, _ = checkpoint

    monkeypatch.setenv("VDT_ROUTER", "1")
    routed_engine = make_engine(path, data_parallel_size=2)
    assert routed_engine.engine_core.router is not None
    routed_outs = _session_traffic(routed_engine, "routed")
    routed_hit = _window_hit_rate(routed_engine)
    router_stats = routed_engine.engine_core.get_stats()["router"]

    monkeypatch.setenv("VDT_ROUTER", "0")
    rr_engine = make_engine(path, data_parallel_size=2)
    assert rr_engine.engine_core.router is None
    rr_outs = _session_traffic(rr_engine, "rr")
    rr_hit = _window_hit_rate(rr_engine)

    # Same traffic, same greedy tokens — routing only moves placement.
    assert routed_outs == rr_outs
    # The whole point: session turns route home, so the fleet prefix
    # cache actually hits.
    assert routed_hit > rr_hit
    # Turns 2..n all found their home replica.
    assert router_stats["affinity_hits"] >= 6
    assert router_stats["requests_routed"] == 9
