"""Structured output: grammar-constrained decoding end to end.

Model: reference tests/v1/entrypoints + v1/structured_output — a grammar
compiled beside the scheduler produces per-step token bitmasks that the
sampler applies, so EVERY generation is valid under the grammar, whatever
the (here: random-weight) model wants to emit."""

import json

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

EOS = 1

# Synthetic token-id -> bytes table (the engine runs tokenizer-free; the
# grammar layer only needs token byte strings).
VOCAB = {
    10: b"y", 11: b"e", 12: b"s", 13: b"n", 14: b"o",
    20: b"{", 21: b"}", 22: b'"a"', 23: b":", 24: b"true",
    25: b"false", 26: b",", 27: b'"b"', 28: b"1", 29: b"2",
    30: b"12", 31: b'"xy"', 32: b"[", 33: b"]", 34: b"yes", 35: b"may",
}


def vocab_bytes(size=128):
    out = [b""] * size
    for tid, bs in VOCAB.items():
        out[tid] = bs
    return out


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=EOS)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_so")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    engine = LLMEngine(EngineArgs(**args).create_engine_config())
    core = engine.engine_core.engine_core
    core.config.model_config.structured_vocab_bytes = vocab_bytes()
    return engine


def run_one(engine, prompt, sp, tag="req"):
    engine.add_request(tag, prompt, sp)
    for _ in range(200):
        for out in engine.step():
            if out.finished:
                return out
    raise AssertionError("request did not finish")


def decode(token_ids):
    vb = vocab_bytes()
    return b"".join(vb[t] for t in token_ids if t != EOS)


def test_guided_choice_always_valid(checkpoint):
    engine = make_engine(checkpoint)
    # Sampled at temperature 1: without the grammar a random model would
    # emit arbitrary tokens; the mask forces one of the choices.
    for i in range(4):
        sp = SamplingParams(temperature=1.0, seed=i, max_tokens=16,
                            structured={"choice": ["yes", "no"]})
        out = run_one(engine, [3, 17, 92, 45 + i], sp, tag=f"c-{i}")
        text = decode(out.outputs[0].token_ids).decode()
        assert text in ("yes", "no"), (i, text, out.outputs[0].token_ids)
        # Finished via EOS once the grammar completed, not by max_tokens.
        assert out.outputs[0].finish_reason == "stop"


def test_guided_regex_constrains_and_terminates(checkpoint):
    engine = make_engine(checkpoint)
    sp = SamplingParams(temperature=1.0, seed=7, max_tokens=20,
                        structured={"regex": r"(yes|maybe)"})
    out = run_one(engine, [5, 9, 33], sp)
    text = decode(out.outputs[0].token_ids).decode()
    assert text in ("yes", "maybe"), text


def test_json_schema_output_parses(checkpoint):
    """The flagship served-json guarantee: output ALWAYS parses and
    matches the schema's required shape."""
    engine = make_engine(checkpoint)
    schema = {"type": "object",
              "properties": {"a": {"type": "boolean"}},
              "required": ["a"]}
    for i in range(3):
        sp = SamplingParams(temperature=1.0, seed=100 + i, max_tokens=30,
                            structured={"json": schema})
        out = run_one(engine, [7, 11, 13 + i], sp, tag=f"j-{i}")
        text = decode(out.outputs[0].token_ids).decode()
        parsed = json.loads(text)
        assert isinstance(parsed.get("a"), bool), text


def test_json_object_mode_parses(checkpoint):
    engine = make_engine(checkpoint)
    sp = SamplingParams(temperature=1.0, seed=3, max_tokens=40,
                        structured={"json_object": True})
    out = run_one(engine, [2, 4, 6], sp)
    text = decode(out.outputs[0].token_ids).decode()
    parsed = json.loads(text)
    assert isinstance(parsed, dict), text


def test_structured_mixes_with_plain_requests(checkpoint):
    """A structured request and a plain one share a batch; the plain
    request's sampling must be unaffected (mask rows default to
    all-True)."""
    engine = make_engine(checkpoint)
    plain_base = run_one(make_engine(checkpoint), [3, 17, 92],
                         SamplingParams(temperature=0.0, max_tokens=6,
                                        ignore_eos=True))
    sp_s = SamplingParams(temperature=1.0, seed=1, max_tokens=16,
                          structured={"choice": ["yes", "no"]})
    sp_p = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    engine.add_request("s-0", [5, 9, 33], sp_s)
    engine.add_request("p-0", [3, 17, 92], sp_p)
    done = {}
    for _ in range(200):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if len(done) == 2:
            break
    assert len(done) == 2
    assert decode(done["s-0"].outputs[0].token_ids).decode() in \
        ("yes", "no")
    assert done["p-0"].outputs[0].token_ids == \
        plain_base.outputs[0].token_ids
