"""Engine core in a subprocess: parity, shutdown, and death handling
(reference: vllm/v1/engine/core.py:362 EngineCoreProc,
tests/v1/shutdown/)."""

import os
import time

import pytest

from tests.engine.test_llm_engine import (checkpoint, hf_greedy,  # noqa: F401
                                          make_engine, run_engine)
from vllm_distributed_tpu.engine.core_client import EngineDeadError
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture()
def mp_env(monkeypatch):
    # The spawned child must pin the CPU platform itself (the tunnelled
    # TPU plugin ignores the JAX_PLATFORMS env var).
    monkeypatch.setenv("VDT_PLATFORM", "cpu")
    monkeypatch.setenv("VDT_RPC_TIMEOUT", "300")


def test_mp_engine_parity_and_shutdown(checkpoint, mp_env):
    path, hf = checkpoint
    engine = make_engine(path, multiprocess_engine_core=True)
    try:
        proc = engine.engine_core.proc
        assert proc.is_alive()
        prompts = [[3, 17, 92, 45, 8], [5, 9, 101], [120, 44]]
        sps = [SamplingParams(temperature=0.0, max_tokens=6,
                              ignore_eos=True) for _ in prompts]
        outs = run_engine(engine, prompts, sps)
        for prompt, out in zip(prompts, outs):
            assert out.outputs[0].token_ids == hf_greedy(hf, prompt, 6), \
                f"mp-engine mismatch for prompt {prompt}"
        # Utility RPC round-trip.
        stats = engine.get_stats()
        assert isinstance(stats, dict) and "hits" in stats
    finally:
        engine.shutdown()
    deadline = time.time() + 10
    while proc.is_alive() and time.time() < deadline:
        time.sleep(0.1)
    assert not proc.is_alive(), "engine core proc must exit on shutdown"


def test_mp_engine_dead_raises(checkpoint, mp_env):
    path, _ = checkpoint
    engine = make_engine(path, multiprocess_engine_core=True)
    try:
        proc = engine.engine_core.proc
        proc.kill()
        proc.join(timeout=10)
        with pytest.raises(EngineDeadError):
            engine.add_request("r0", [3, 4, 5],
                               SamplingParams(temperature=0.0,
                                              max_tokens=4))
            for _ in range(50):
                engine.step()
    finally:
        try:
            engine.shutdown()
        except Exception:
            pass
