"""DP replica failover, resurrection, and admission accounting.

Stub-client drills over DPEngineClient's balancer: a dead replica goes
out of rotation and its journaled requests migrate as continuation
prefills; a downed replica resurrects via the probe; coordinator
admission counts never go negative and double-finish is idempotent
under replay."""

import pytest

from tests.conftest import make_config
from vllm_distributed_tpu.core.sched.scheduler import EngineCoreOutput
from vllm_distributed_tpu.engine import dp_client as dp_mod
from vllm_distributed_tpu.engine.core_client import (EngineCoreClient,
                                                     EngineDeadError)
from vllm_distributed_tpu.engine.dp_client import DPEngineClient
from vllm_distributed_tpu.request import EngineCoreRequest
from vllm_distributed_tpu.sampling_params import SamplingParams

pytestmark = pytest.mark.faults


class _StubReplica(EngineCoreClient):
    """Scriptable replica: records adds/aborts, serves queued output
    batches, and can be declared dead / revived."""

    def __init__(self, config) -> None:
        self.config = config
        self.added: list[EngineCoreRequest] = []
        self.aborted: list[str] = []
        self.outputs: list[list[EngineCoreOutput]] = []
        self.dead = False
        self.fail_restart = False
        self.restarts = 0

    def _check(self) -> None:
        if self.dead:
            raise EngineDeadError("stub replica is dead")

    def add_request(self, request: EngineCoreRequest) -> None:
        self._check()
        self.added.append(request)

    def abort_requests(self, request_ids: list[str]) -> None:
        self._check()
        self.aborted.extend(request_ids)

    def recv_outputs(self, timeout_ms: int):
        self._check()
        return self.outputs.pop(0) if self.outputs else None

    def restart(self) -> None:
        if self.fail_restart:
            raise EngineDeadError("stub replica refuses to restart")
        self.dead = False
        self.restarts += 1

    def shutdown(self) -> None:
        pass


@pytest.fixture
def dp2(monkeypatch):
    """DPEngineClient over two stub replicas (mp transport shape)."""
    config = make_config()
    config.parallel_config.data_parallel_size = 2
    config.fault_tolerance_config.replica_probe_interval_s = 0.01
    config.fault_tolerance_config.restart_backoff_base_s = 0.0
    monkeypatch.setattr(dp_mod, "SyncMPClient", _StubReplica)
    client = DPEngineClient(config, force_mp=True)
    return client


def _req(rid: str, max_tokens: int = 16) -> EngineCoreRequest:
    return EngineCoreRequest(
        request_id=rid, prompt_token_ids=[1, 2, 3],
        sampling_params=SamplingParams(temperature=0.0,
                                       max_tokens=max_tokens))


def _out(rid: str, tokens: list[int],
         finish: str = None) -> EngineCoreOutput:
    return EngineCoreOutput(req_id=rid, new_token_ids=tokens,
                            finish_reason=finish)


def test_routing_balances_by_live_count(dp2):
    for i in range(4):
        dp2.add_request(_req(f"r{i}"))
    assert dp2.request_counts() == [2, 2]
    assert len(dp2.clients[0].added) == 2
    assert len(dp2.clients[1].added) == 2


def test_failover_migrates_inflight_as_continuations(dp2):
    dp2.add_request(_req("a", max_tokens=10))
    dp2.add_request(_req("b"))
    owner_a = dp2._owner["a"]
    victim, survivor = dp2.clients[owner_a], \
        dp2.clients[1 - owner_a]
    # "a" streams two tokens before its replica dies.
    victim.outputs.append([_out("a", [7, 9])])
    dp2.recv_outputs(timeout_ms=10)
    assert dp2._progress["a"] == [7, 9]

    victim.dead = True
    dp2.recv_outputs(timeout_ms=10)

    assert dp2.replica_failovers == 1
    assert owner_a in dp2._down
    # "a" migrated as a continuation prefill: prompt absorbed the two
    # delivered tokens, budget shrank accordingly.
    migrated = {r.request_id: r for r in survivor.added}
    assert migrated["a"].prompt_token_ids == [1, 2, 3, 7, 9]
    assert migrated["a"].sampling_params.max_tokens == 8
    # every stranded request now lives on the survivor
    assert all(dp2._owner[rid] == 1 - owner_a for rid in ("a", "b")
               if rid in dp2._owner)
    assert dp2._live[owner_a] == set()


def test_admission_failover_retries_on_healthy_replica(dp2):
    dp2.clients[0].dead = True
    dp2.add_request(_req("x"))
    assert dp2._owner["x"] == 1
    assert 0 in dp2._down
    assert dp2.replica_failovers == 1


def test_all_replicas_dead_is_terminal(dp2):
    dp2.clients[0].dead = True
    dp2.clients[1].dead = True
    with pytest.raises(EngineDeadError):
        dp2.add_request(_req("x"))
    # Output path surfaces the deployment-wide death too (so the
    # upstream supervisor can attempt a full-fleet restart).
    with pytest.raises(EngineDeadError):
        dp2.recv_outputs(timeout_ms=10)
        dp2.recv_outputs(timeout_ms=10)


def test_resurrection_probe_restores_rotation(dp2):
    import time
    dp2.clients[0].dead = True
    dp2.add_request(_req("x"))  # discovers the death, fails over
    assert 0 in dp2._down
    dp2.clients[0].dead = False  # stub: restart() will succeed
    # The probe runs on a thread; poll until its result is applied.
    deadline = time.monotonic() + 5.0
    while 0 in dp2._down and time.monotonic() < deadline:
        time.sleep(0.02)
        dp2.recv_outputs(timeout_ms=10)
    assert 0 not in dp2._down
    assert dp2.clients[0].restarts == 1
    assert dp2.replica_resurrections == 1


def test_resurrection_budget_circuit_breaks(dp2):
    import time
    cfgd = dp2._supervisors[0]
    dp2.clients[0].dead = True
    dp2.clients[0].fail_restart = True
    dp2.add_request(_req("x"))
    deadline = time.monotonic() + 5.0
    while not cfgd.exhausted and time.monotonic() < deadline:
        time.sleep(0.02)
        dp2.recv_outputs(timeout_ms=10)
    # Let the last failed probe report back, then confirm no more run.
    time.sleep(0.05)
    dp2.recv_outputs(timeout_ms=10)
    assert 0 in dp2._down
    assert dp2.clients[0].restarts == 0
    assert cfgd.exhausted


def test_full_fleet_restart_clears_balancer_state(dp2):
    dp2.add_request(_req("x"))
    dp2.clients[0].dead = True
    dp2.clients[1].dead = True
    dp2.clients[0].fail_restart = False
    dp2.clients[1].fail_restart = False
    # restart() must revive stubs even though they are "dead"
    dp2.restart()
    assert dp2._down == set()
    assert dp2._owner == {} and dp2._requests == {}
    assert all(not c.dead for c in dp2.clients)


# ---------------------------------------------------------------------------
# Coordinator admission accounting (satellite): counts never negative,
# double-finish idempotent under replay.
# ---------------------------------------------------------------------------

class _FakeCoordinator:
    """In-process stand-in for DPCoordinatorClient that enforces the
    never-negative invariant on every report."""

    def __init__(self, n: int) -> None:
        self.counts = [0] * n
        self.healthy = [True] * n

    def route(self, prefer=None) -> int:
        live = [i for i in range(len(self.counts)) if self.healthy[i]]
        assert live, "route() with no healthy engines"
        if prefer is not None and self.healthy[prefer]:
            i = prefer
        else:
            i = min(live, key=self.counts.__getitem__)
        self.counts[i] += 1
        return i

    def report(self, engine: int, delta: int) -> None:
        self.counts[engine] += delta
        assert self.counts[engine] >= 0, (
            f"engine {engine} count went negative: {self.counts}")

    def set_health(self, engine: int, up: bool, *,
                   clear: bool = False) -> None:
        self.healthy[engine] = up
        if clear:
            self.counts[engine] = 0


@pytest.fixture
def dp2c(dp2):
    dp2.coordinator = _FakeCoordinator(2)
    return dp2


def test_abort_unwinds_admission_count(dp2c):
    dp2c.add_request(_req("a"))
    dp2c.add_request(_req("b"))
    assert sum(dp2c.coordinator.counts) == 2
    dp2c.abort_requests(["a", "b"])
    assert dp2c.coordinator.counts == [0, 0]
    # Double abort: no owner left -> no report -> still zero.
    dp2c.abort_requests(["a", "b"])
    assert dp2c.coordinator.counts == [0, 0]


def test_failed_admission_unwinds_route_increment(dp2c):
    dp2c.clients[0].dead = True
    dp2c.clients[1].dead = True
    with pytest.raises(EngineDeadError):
        dp2c.add_request(_req("x"))
    assert dp2c.coordinator.counts == [0, 0]


def test_double_finish_is_idempotent_under_replay(dp2c):
    dp2c.add_request(_req("a"))
    i = dp2c._owner["a"]
    assert dp2c.coordinator.counts[i] == 1
    # The same finish delivered twice (a replayed request's terminal
    # output can race a pre-crash duplicate): the second is a no-op.
    dp2c._mark_finished([_out("a", [5], finish="stop")])
    dp2c._mark_finished([_out("a", [5], finish="stop")])
    assert dp2c.coordinator.counts[i] == 0
    assert "a" not in dp2c._owner and "a" not in dp2c._requests


def test_failover_clears_dead_replica_count(dp2c):
    dp2c.add_request(_req("a"))
    dp2c.add_request(_req("b"))
    victim = dp2c._owner["a"]
    dp2c.clients[victim].dead = True
    dp2c.recv_outputs(timeout_ms=10)
    assert victim in dp2c._down
    assert not dp2c.coordinator.healthy[victim]
    # Migrated load is re-accounted against the survivor only.
    assert dp2c.coordinator.counts[victim] == 0
    assert dp2c.coordinator.counts[1 - victim] == 2
