"""Embedding/pooling API: LLM.encode returns the final-norm last-token
hidden state, matching HF last_hidden_state (model: reference pooling
models tests over the encode path)."""

import numpy as np
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.outputs import PoolingOutput
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_pool")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


PROMPTS = [[3, 17, 92, 45, 8], [5, 9, 33, 71, 14, 62, 77]]


def encode(engine, prompts, tag="e"):
    for i, p in enumerate(prompts):
        engine.add_request(f"{tag}-{i}", p,
                           SamplingParams(temperature=0.0, max_tokens=1),
                           pooling_params={"type": "last"})
    done = {}
    for _ in range(200):
        for out in engine.step():
            if isinstance(out, PoolingOutput) or out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    return [done[f"{tag}-{i}"] for i in range(len(prompts))]


def test_encode_matches_hf_last_hidden_state(checkpoint):
    path, hf = checkpoint
    engine = make_engine(path)
    outs = encode(engine, PROMPTS)
    for prompt, out in zip(PROMPTS, outs):
        assert isinstance(out, PoolingOutput)
        with torch.no_grad():
            want = hf.model(torch.tensor([prompt])
                            ).last_hidden_state[0, -1].numpy()
        got = np.asarray(out.embedding, np.float32)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_encode_mixes_with_generation(checkpoint):
    """Pooling and generation requests share one batch."""
    path, hf = checkpoint
    engine = make_engine(path)
    engine.add_request("gen-0", PROMPTS[0],
                       SamplingParams(temperature=0.0, max_tokens=5,
                                      ignore_eos=True))
    engine.add_request("pool-0", PROMPTS[1], SamplingParams(max_tokens=1),
                       pooling_params={"type": "last"})
    done = {}
    for _ in range(200):
        for out in engine.step():
            if getattr(out, "finished", True):
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert isinstance(done["pool-0"], PoolingOutput)
    assert len(done["gen-0"].outputs[0].token_ids) == 5
    with torch.no_grad():
        want = hf.model(torch.tensor([PROMPTS[1]])
                        ).last_hidden_state[0, -1].numpy()
    np.testing.assert_allclose(np.asarray(done["pool-0"].embedding),
                               want, rtol=2e-4, atol=2e-4)


def test_llm_encode_api(checkpoint):
    path, _ = checkpoint
    from vllm_distributed_tpu.entrypoints.llm import LLM
    llm = LLM(model=path, dtype="float32", block_size=4,
              num_gpu_blocks_override=64, max_model_len=64,
              max_num_batched_tokens=64, max_num_seqs=8,
              skip_tokenizer_init=True)
    outs = llm.encode(PROMPTS)
    assert len(outs) == 2
    assert all(isinstance(o, PoolingOutput) for o in outs)
    assert all(len(o.embedding) == 64 for o in outs)


def test_encode_over_multiprocess_core(checkpoint):
    """pooling_params must survive the ZMQ request codec (subprocess
    engine core)."""
    path, hf = checkpoint
    engine = make_engine(path, multiprocess_engine_core=True)
    try:
        outs = encode(engine, [PROMPTS[0]], tag="mp")
        assert isinstance(outs[0], PoolingOutput)
        with torch.no_grad():
            want = hf.model(torch.tensor([PROMPTS[0]])
                            ).last_hidden_state[0, -1].numpy()
        np.testing.assert_allclose(np.asarray(outs[0].embedding), want,
                                   rtol=2e-4, atol=2e-4)
    finally:
        engine.shutdown()
