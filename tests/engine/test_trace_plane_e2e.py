"""End-to-end trace plane through a real engine: VDT_TRACE_PLANE=1
mints a context at admission, the scheduler stamps its ring events, the
get_stats drain feeds the front-end assembler, and the Perfetto export
renders the stitched trace. Off (the default) the plane must leave no
footprint at all."""

import json

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu import trace_plane as tp
from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64, eos_token_id=1)
    path = tmp_path_factory.mktemp("tiny_trace")
    HFLlama(cfg).eval().save_pretrained(path, safe_serialization=True)
    return str(path)


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config(),
                     load_tokenizer=False)


def run_one(engine, rid: str = "req-0", max_tokens: int = 4):
    engine.add_request(rid, [3, 17, 92, 45],
                       SamplingParams(temperature=0.0,
                                      max_tokens=max_tokens,
                                      ignore_eos=True))
    for _ in range(200):
        for out in engine.step():
            if out.finished:
                return out
    raise AssertionError("request never finished")


def test_plane_off_leaves_no_footprint(checkpoint, monkeypatch):
    monkeypatch.delenv("VDT_TRACE_PLANE", raising=False)
    engine = make_engine(checkpoint)
    assert engine.processor.trace_enabled is False
    assert engine.output_processor.assembler is None
    req = engine.processor.process_inputs(
        "probe", [1, 2, 3],
        SamplingParams(temperature=0.0, max_tokens=1))
    assert req.trace_ctx is None  # nothing minted -> old wire bytes


def test_traced_request_assembles_and_exports(checkpoint, monkeypatch):
    monkeypatch.setenv("VDT_TRACE_PLANE", "1")
    engine = make_engine(checkpoint)
    asm = engine.output_processor.assembler
    assert asm is not None
    run_one(engine, rid="req-0")
    # The stats poll drains the core ring into the assembler (the same
    # path GET /debug/trace uses).
    engine.get_stats()
    trace = asm.get(request_id="req-0")
    assert trace is not None
    assert trace["trace_id"] == tp.mint_trace_ctx("req-0")["trace_id"]
    assert trace["request_ids"] == ["req-0"]
    names = [e[2] for e in trace["events"]]
    # Front-end admission + the scheduler lifecycle in ONE trace.
    assert ev.ARRIVED in names
    assert ev.QUEUED in names and ev.SCHEDULED in names
    assert ev.FINISHED in names
    # Core-ring events carry the stamp (that is what crosses replicas).
    stamped = [e for e in trace["events"]
               if isinstance(e[3], dict) and ev.TRACE_KEY in e[3]]
    assert stamped
    # The export is valid Chrome/Perfetto trace-event JSON, rendered
    # in time order (the assembler keeps feed order; the exporter
    # sorts after the epoch rebase).
    out = tp.perfetto(trace)
    json.dumps(out)
    instants = [e["ts"] for e in out["traceEvents"] if e["ph"] == "i"]
    assert instants == sorted(instants) and instants[0] >= 0
    assert out["otherData"]["trace_id"] == trace["trace_id"]
    assert any(e["ph"] == "X" for e in out["traceEvents"])
    assert any(e["ph"] == "i" and e["tid"] == "scheduler"
               for e in out["traceEvents"])


def test_disagg_handoff_stitches_two_replicas(checkpoint, monkeypatch):
    """ISSUE 19 acceptance: ONE disagg request yields ONE trace with
    spans from BOTH replicas (prefill producer + decode consumer) and
    an explicit Perfetto flow link across the KV handoff."""
    import time

    monkeypatch.setenv("VDT_TRACE_PLANE", "1")
    monkeypatch.setenv("VDT_DISAGG", "1")
    engine = make_engine(checkpoint, data_parallel_size=2,
                         num_gpu_blocks_override=64)
    sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)
    for i in range(2):
        engine.add_request(f"dh-{i}", [3, 17, 92, 45, 8, 21, 33, 64],
                           sp)
    for _ in range(20000):
        engine.step()
        if not engine.has_unfinished_requests():
            break
        time.sleep(0.001)  # the pull threads need GIL slots
    assert not engine.has_unfinished_requests()
    engine.get_stats()  # drain both replicas' rings + the router ring
    asm = engine.output_processor.assembler
    trace = asm.get(request_id="dh-0")
    assert trace is not None
    # Spans from both replicas stitched under the one trace id.
    assert asm.replica_count(trace) >= 2
    names = [e[2] for e in trace["events"]]
    assert ev.DISAGG_HANDOFF in names
    assert any(n in names for n in (ev.KV_PULL_WAIT, ev.KV_PULL_DONE,
                                    ev.KV_PULL_LOCAL))
    out = tp.perfetto(trace)
    json.dumps(out)
    flow_s = [e for e in out["traceEvents"] if e["ph"] == "s"]
    flow_f = [e for e in out["traceEvents"] if e["ph"] == "f"]
    assert flow_s and flow_f, "handoff flow arrow missing"
    assert flow_s[0]["id"] == flow_f[0]["id"]
    # The producer's and consumer's spans live on different pid lanes.
    assert {e["pid"] for e in out["traceEvents"]
            if e["ph"] == "i"} >= {0, 1}
    engine.shutdown()


def test_trace_plane_implies_timeline(checkpoint, monkeypatch):
    # VDT_TRACE_PLANE=1 with the timeline flag untouched must still
    # record lifecycle events — an empty trace would be a footgun.
    monkeypatch.setenv("VDT_TRACE_PLANE", "1")
    monkeypatch.delenv("VDT_REQUEST_TIMELINE", raising=False)
    assert ev.timeline_enabled()
    monkeypatch.setenv("VDT_TRACE_PLANE", "0")
    monkeypatch.setenv("VDT_REQUEST_TIMELINE", "0")
    assert not ev.timeline_enabled()


def test_burn_watchdog_gated_and_degrades(checkpoint, monkeypatch):
    # No SLO target -> no watchdog at all.
    monkeypatch.delenv("VDT_SLO_TTFT_MS", raising=False)
    monkeypatch.delenv("VDT_SLO_TPOT_MS", raising=False)
    engine = make_engine(checkpoint)
    assert engine.output_processor.stats.burn is None

    # An unmeetable TTFT target: every request misses, both burn
    # windows blow past the threshold, the degraded flag trips and the
    # gauges render.
    monkeypatch.setenv("VDT_SLO_TTFT_MS", "0.000001")
    engine = make_engine(engine.config.model_config.model)
    stats = engine.output_processor.stats
    assert stats.burn is not None
    for i in range(3):
        run_one(engine, rid=f"burn-{i}")
    rates = stats.burn.burn_rates()
    assert rates["1m"] > 2.0 and rates["10m"] > 2.0
    assert stats.burn.degraded()
    text = stats.render()
    assert 'vdt:slo_burn_rate{window="1m"}' in text
    assert 'vdt:slo_burn_rate{window="10m"}' in text
    assert "vdt:slo_degraded 1" in text
