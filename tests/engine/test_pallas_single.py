from tests.engine.test_llm_engine import (checkpoint, make_engine, hf_greedy,
                                          run_engine)
from vllm_distributed_tpu.sampling_params import SamplingParams


def test_debug_single(checkpoint, monkeypatch):
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    path, hf = checkpoint
    engine = make_engine(path, max_num_batched_tokens=16)
    prompt = [3, 17, 92, 45, 8]
    sp = SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True)
    outs = run_engine(engine, [prompt], [sp])
    got = outs[0].outputs[0].token_ids
    want = hf_greedy(hf, prompt, 5)
    print("single got :", got)
    print("single want:", want)
    assert got == want
