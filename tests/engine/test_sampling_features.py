"""End-to-end tests for the extended sampling path (penalties, logit
bias, allowed tokens, min_tokens, logprobs=k) through the full engine
(model: reference tests/v1/sample/ + tests/entrypoints behavior)."""

import numpy as np
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_feat")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


@pytest.fixture(scope="module")
def engine(checkpoint):
    path, _ = checkpoint
    return LLMEngine(EngineArgs(
        model=path, dtype="float32", block_size=4,
        num_gpu_blocks_override=128, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
    ).create_engine_config(), load_tokenizer=False)


_RUN = [0]


def run(engine, prompt, sp):
    _RUN[0] += 1
    engine.add_request(f"feat-{_RUN[0]}", prompt, sp)
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                return out
    raise AssertionError("engine did not finish")


def hf_stepwise_greedy(hf, prompt, n, penalty_fn):
    """Greedy decode with a numpy logits post-processor applied per step:
    the exact reference for penalty semantics."""
    tokens = list(prompt)
    out = []
    for _ in range(n):
        with torch.no_grad():
            logits = hf(torch.tensor([tokens])).logits[0, -1].numpy().copy()
        logits = penalty_fn(logits, tokens, out)
        tok = int(np.argmax(logits))
        tokens.append(tok)
        out.append(tok)
    return out


def test_repetition_penalty_matches_manual_reference(engine, checkpoint):
    _, hf = checkpoint
    prompt = [3, 17, 92, 45, 8]
    rp = 1.7

    def penalize(logits, tokens, out):
        seen = set(tokens)
        for t in seen:
            logits[t] = logits[t] / rp if logits[t] > 0 else logits[t] * rp
        return logits

    expect = hf_stepwise_greedy(hf, prompt, 6, penalize)
    got = run(engine, prompt,
              SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True,
                             repetition_penalty=rp))
    assert got.outputs[0].token_ids == expect


def test_frequency_presence_penalties_match_manual_reference(
        engine, checkpoint):
    _, hf = checkpoint
    prompt = [5, 9, 33, 71]
    fp, pp = 0.9, 0.6

    def penalize(logits, tokens, out):
        counts = np.bincount(out, minlength=128) if out else np.zeros(128)
        return logits - fp * counts - pp * (counts > 0)

    expect = hf_stepwise_greedy(hf, prompt, 6, penalize)
    got = run(engine, prompt,
              SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True,
                             frequency_penalty=fp, presence_penalty=pp))
    assert got.outputs[0].token_ids == expect


def test_logit_bias_forces_token(engine):
    got = run(engine, [3, 17, 92],
              SamplingParams(temperature=0.0, max_tokens=3, ignore_eos=True,
                             logit_bias={77: 100.0}))
    assert got.outputs[0].token_ids == [77, 77, 77]


def test_allowed_token_ids_restricts_output(engine):
    allowed = [10, 11, 12]
    got = run(engine, [3, 17, 92],
              SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True,
                             allowed_token_ids=allowed))
    assert set(got.outputs[0].token_ids) <= set(allowed)


def test_min_tokens_suppresses_eos(engine):
    # Bias pushes EOS (id 1) to the top; min_tokens must suppress it for
    # the first 3 tokens, after which the request stops on EOS.
    got = run(engine, [3, 17, 92],
              SamplingParams(temperature=0.0, max_tokens=10, min_tokens=3,
                             logit_bias={1: 100.0}))
    toks = got.outputs[0].token_ids
    assert len(toks) == 4
    assert all(t != 1 for t in toks[:3])
    assert toks[3] == 1
    assert got.outputs[0].finish_reason == "stop"


def test_logprobs_k_returned(engine):
    k = 5
    got = run(engine, [3, 17, 92, 45],
              SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True,
                             logprobs=k))
    comp = got.outputs[0]
    assert comp.logprobs is not None
    assert len(comp.logprobs) == len(comp.token_ids)
    for tok, lp in zip(comp.token_ids, comp.logprobs):
        # Sampled token first; at least k entries; greedy sample = top-1,
        # so its logprob is the max.
        keys = list(lp)
        assert keys[0] == tok
        assert len(lp) >= k
        assert abs(lp[tok] - max(lp.values())) < 1e-6
    # Cumulative logprob equals the sum of sampled-token logprobs.
    expect_cum = sum(lp[t] for t, lp in zip(comp.token_ids, comp.logprobs))
    np.testing.assert_allclose(comp.cumulative_logprob, expect_cum,
                               rtol=1e-6)


def test_plain_requests_unaffected(engine, checkpoint):
    """A penalty-free request decodes on the fast path and still matches
    HF greedy exactly."""
    _, hf = checkpoint
    prompt = [7, 44, 101, 13]
    with torch.no_grad():
        out = hf.generate(torch.tensor([prompt]), max_new_tokens=5,
                          do_sample=False, eos_token_id=None)
    expect = out[0].tolist()[len(prompt):]
    got = run(engine, prompt,
              SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True))
    assert got.outputs[0].token_ids == expect


def test_oversized_sampler_buffer_rejected_at_admission():
    """min_tokens stop suppression shares the static sampler buffer with
    logit_bias; an over-budget combination must be rejected when the
    SamplingParams is constructed, never inside the engine step (which
    would kill every in-flight request)."""
    from vllm_distributed_tpu.sampling_params import (BIAS_BUF_WIDTH,
                                                      MAX_BIAS_ENTRIES)
    # Stop ids alone overflowing the buffer.
    with pytest.raises(ValueError, match="sampler-buffer"):
        SamplingParams(min_tokens=1,
                       stop_token_ids=list(range(BIAS_BUF_WIDTH)))
    # Max bias entries plus enough DISJOINT stop ids to spill (the
    # runner merges by token id, so only the union counts).
    with pytest.raises(ValueError, match="sampler-buffer"):
        SamplingParams(min_tokens=1,
                       logit_bias={t: 1.0 for t in range(MAX_BIAS_ENTRIES)},
                       stop_token_ids=list(
                           range(MAX_BIAS_ENTRIES, BIAS_BUF_WIDTH + 1)))
    # Overlapping stop ids cost nothing extra.
    SamplingParams(min_tokens=1,
                   logit_bias={t: 1.0 for t in range(MAX_BIAS_ENTRIES)},
                   stop_token_ids=list(range(16)))
    # The same shapes are fine without min_tokens (stops never enter the
    # buffer) or within budget.
    SamplingParams(stop_token_ids=list(range(BIAS_BUF_WIDTH)))
    SamplingParams(min_tokens=1,
                   logit_bias={t: 1.0 for t in range(MAX_BIAS_ENTRIES)},
                   stop_token_ids=[1, 2, 3])


def test_penalty_history_uploads_are_incremental(checkpoint):
    """The device-resident history mirror uploads a full
    [row, max_model_len] row only on admission/rewrites; steady-state
    decode ships only the per-step delta tokens, so host->device bytes
    per penalty step are independent of max_model_len (ADVICE r2 #3 /
    VERDICT r3 weak #6)."""
    path, _ = checkpoint
    engine = LLMEngine(EngineArgs(
        model=path, dtype="float32", block_size=4,
        num_gpu_blocks_override=128, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
    ).create_engine_config(), load_tokenizer=False)
    runner = (engine.engine_core.engine_core.executor
              .worker.model_runner)
    calls = {"full": 0, "delta": 0}
    orig_full = runner._hist_apply_full
    orig_delta = runner._hist_apply_delta

    def spy_full(*a, **k):
        calls["full"] += 1
        return orig_full(*a, **k)

    def spy_delta(*a, **k):
        calls["delta"] += 1
        return orig_delta(*a, **k)

    runner._hist_apply_full = spy_full
    runner._hist_apply_delta = spy_delta
    engine.add_request(
        "hist-0", [3, 17, 92, 45],
        SamplingParams(temperature=0.0, max_tokens=12, ignore_eos=True,
                       presence_penalty=0.5))
    while engine.has_unfinished_requests():
        engine.step()
    # One full upload at admission; every later step is a small delta.
    assert calls["full"] == 1, calls
    assert calls["delta"] >= 8, calls
