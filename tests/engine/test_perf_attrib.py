"""Engine-level gates for the performance-attribution plane (ISSUE 14).

Acceptance contract: a CPU-smoke greedy run with telemetry on reports
mfu > 0 / mbu > 0, total charged FLOPs within 2% of the analytic cost
model applied to the run's exact composition, /metrics renders the new
families, /debug/perf returns a non-empty self-consistent table;
``VDT_PERF_ATTRIB=0`` constructs no cost model and adds no stats keys
(token-identical outputs). Plus the hardened profiler capture: one
capture at a time, auto-named dirs, and the ``perf.capture_stall``
drill proving a wedged xprof session is bounded by VDT_PROFILE_MAX_S
without wedging serving."""

import asyncio
import time

import pytest
from transformers import LlamaConfig

from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                         LoadConfig, ModelConfig,
                                         SchedulerConfig)
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

HF = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
          num_hidden_layers=2, num_attention_heads=4,
          num_key_value_heads=2, max_position_embeddings=256,
          architectures=["LlamaForCausalLM"])

B, P, D = 3, 10, 5


def make_engine() -> LLMEngine:
    config = EngineConfig(
        model_config=ModelConfig(model="tiny-perf-dummy",
                                 dtype="float32", max_model_len=256,
                                 hf_overrides=HF,
                                 skip_tokenizer_init=True),
        cache_config=CacheConfig(block_size=4,
                                 num_gpu_blocks_override=256),
        scheduler_config=SchedulerConfig(max_num_batched_tokens=256,
                                         max_num_seqs=8,
                                         max_model_len=256),
        load_config=LoadConfig(load_format="dummy"))
    config.model_config.hf_config = LlamaConfig(**HF)
    return LLMEngine(config, load_tokenizer=False)


def run_greedy(engine) -> dict:
    # DISTINCT prompts: identical prompts prefix-cache-hit and the
    # engine honestly charges the smaller computed composition, which
    # would make the closed-form prediction below wrong.
    sp = SamplingParams(temperature=0.0, max_tokens=D, ignore_eos=True)
    for i in range(B):
        engine.add_request(f"r{i}",
                           [2 + i * 17 + j for j in range(P)], sp)
    toks = {}
    for _ in range(200):
        for out in engine.step():
            if out.finished:
                toks[out.request_id] = list(out.outputs[0].token_ids)
        if not engine.has_unfinished_requests():
            break
    assert len(toks) == B
    return toks


def _runner(engine):
    return engine.engine_core.engine_core.executor.worker.model_runner


def expected_flops(cm) -> float:
    """Closed-form analytic prediction for the fixture workload: one
    un-chunked prefill wave (budget >= B*P) + D-1 decode waves, every
    wave sampling one row per scheduled request."""
    total = 0.0
    for _ in range(B):
        total += (P * cm.linear_flops_per_token +
                  (P * (P + 1) / 2) * cm.attn_flops_per_token_kv +
                  cm.lm_head_flops_per_row)
        for j in range(1, D):
            total += (cm.linear_flops_per_token +
                      (P + j) * cm.attn_flops_per_token_kv +
                      cm.lm_head_flops_per_row)
    return total


def test_greedy_run_reports_mfu_mbu_and_matches_analytic():
    engine = make_engine()
    try:
        run_greedy(engine)
        stats = engine.get_stats()
        cm = _runner(engine).model.cfg.cost_model
        assert cm is not None
        # Totals match the analytic model on the exact composition.
        exp = expected_flops(cm)
        assert stats["model_flops"] == pytest.approx(exp, rel=0.02)
        # Utilization gauges live and positive, per labeled worker.
        workers = stats["workers"]
        (label, w), = workers.items()
        assert w["mfu"] > 0 and w["mbu"] > 0
        hbm = stats["hbm_bytes"]
        assert set(hbm) == {"weights", "kv_read", "kv_write",
                            "activations"}
        assert all(v > 0 for v in hbm.values())
        # Attribution table keyed kernel/phase/bucket, both phases hit.
        phases = {k.split("/")[1] for k in stats["perf_attrib"]}
        assert {"prefill", "decode"} <= phases
        assert set(stats["perf_phases"]) >= {"prefill", "decode"}
        # /metrics renders every new family.
        from vllm_distributed_tpu.metrics.prometheus import \
            render_metrics
        text = render_metrics(stats)
        for needle in (f'vdt:mfu{{worker="{label}"}}',
                       f'vdt:mbu{{worker="{label}"}}',
                       'vdt:hbm_bytes_total{kind="kv_read"}',
                       'vdt:roofline_bound{phase="decode"}',
                       "vdt:model_flops_total"):
            assert needle in text, needle
    finally:
        engine.engine_core.shutdown()


def test_debug_perf_table_is_self_consistent():
    engine = make_engine()
    try:
        run_greedy(engine)
        stats = engine.get_stats()

        class _Stub:
            async def get_stats(self, include_events=True):
                assert include_events is False
                return stats

        from vllm_distributed_tpu.entrypoints.openai.api_server import \
            _debug_perf_json
        perf = asyncio.run(_debug_perf_json(_Stub()))
        rows = perf["attribution"]
        assert rows, "attribution table must not be empty"
        assert rows == sorted(rows, key=lambda r: r["device_seconds"],
                              reverse=True)
        table_flops = sum(r["flops"] for r in rows)
        assert table_flops == pytest.approx(
            perf["totals"]["model_flops"], rel=0.02)
        assert perf["utilization"]
        assert set(perf["roofline_bound"]) >= {"prefill", "decode"}
        for r in rows:
            assert r["kernel"] and r["phase"] in ("prefill", "decode",
                                                  "mixed")
    finally:
        engine.engine_core.shutdown()


def test_perf_attrib_off_is_clean_and_token_identical(monkeypatch):
    engine_on = make_engine()
    try:
        base = run_greedy(engine_on)
    finally:
        engine_on.engine_core.shutdown()
    monkeypatch.setenv("VDT_PERF_ATTRIB", "0")
    engine = make_engine()
    try:
        toks = run_greedy(engine)
        assert toks == base
        assert _runner(engine).model.cfg.cost_model is None
        stats = engine.get_stats()
        for key in ("model_flops", "hbm_bytes", "perf_attrib",
                    "perf_phases", "perf_peaks"):
            assert key not in stats, key
        workers = stats.get("workers") or {}
        for w in workers.values():
            assert "mfu" not in w and "mbu" not in w
        from vllm_distributed_tpu.metrics.prometheus import \
            render_metrics
        text = render_metrics(stats)
        assert "vdt:mfu" not in text
        assert "vdt:roofline_bound" not in text
    finally:
        engine.engine_core.shutdown()


def test_profiler_capture_hardening(monkeypatch, tmp_path):
    monkeypatch.setenv("VDT_PROFILER_DIR", str(tmp_path))
    engine = make_engine()
    core = engine.engine_core.engine_core
    try:
        with pytest.raises(ValueError, match="no profiler capture"):
            core.profile("stop")
        d1 = core.profile("start")
        assert str(tmp_path) in d1
        with pytest.raises(ValueError, match="already active"):
            core.profile("start")
        assert core.profile("stop") == d1
        # Auto-naming: a second capture gets a DIFFERENT directory.
        d2 = core.profile("start")
        assert d2 != d1
        core.profile("stop")
    finally:
        engine.engine_core.shutdown()


def test_capture_stall_drill_bounded_by_deadline(monkeypatch,
                                                 tmp_path):
    """perf.capture_stall: the stop RPC is lost (wedged xprof client);
    the VDT_PROFILE_MAX_S deadline force-stops the capture from the
    step loop while serving keeps producing tokens, and the fault fire
    is counted."""
    from vllm_distributed_tpu.utils import fault_injection as fi
    monkeypatch.setenv("VDT_PROFILER_DIR", str(tmp_path))
    monkeypatch.setenv("VDT_PROFILE_MAX_S", "0.2")
    engine = make_engine()
    core = engine.engine_core.engine_core
    fi.inject("perf.capture_stall")
    try:
        core.profile("start")
        assert core._profile_stalled
        with pytest.raises(RuntimeError, match="wedged"):
            core.profile("stop")
        assert core._profile_dir is not None
        time.sleep(0.25)
        toks = run_greedy(engine)  # serving survives the wedge
        assert all(len(t) == D for t in toks.values())
        assert core._profile_dir is None, "deadline must force-stop"
        assert fi.counters().get("perf.capture_stall", 0) >= 1
        # The lane is free again: a fresh capture starts cleanly.
        fi.clear("perf.capture_stall")
        d = core.profile("start")
        assert core.profile("stop") == d
    finally:
        fi.clear()
        engine.engine_core.shutdown()
