"""Unit tests for the regex->byte-DFA compiler's edge cases
(ADVICE round 3: {0} bounds, non-ASCII class members/escapes)."""

import pytest

from vllm_distributed_tpu.structured_output.fsm import compile_regex


def _accepts(dfa, text) -> bool:
    data = text.encode("utf-8") if isinstance(text, str) else text
    state = dfa.walk_bytes(1, data)
    return state != 0 and bool(dfa.accept[state])


@pytest.mark.parametrize("pattern", ["a{0}b", "a{0,0}b"])
def test_zero_repeat_is_epsilon(pattern):
    dfa = compile_regex(pattern)
    assert _accepts(dfa, "b")
    assert not _accepts(dfa, "ab")
    assert not _accepts(dfa, "aab")


def test_bounded_repeats_still_work():
    dfa = compile_regex("a{2,3}b")
    assert not _accepts(dfa, "ab")
    assert _accepts(dfa, "aab")
    assert _accepts(dfa, "aaab")
    assert not _accepts(dfa, "aaaab")


def test_nonascii_class_member_matches_full_sequence():
    dfa = compile_regex("[é]")
    assert _accepts(dfa, "é")
    assert not _accepts(dfa, b"\xc3")   # lone lead byte
    assert not _accepts(dfa, b"\xa9")   # lone continuation byte


def test_mixed_class_ascii_and_multibyte():
    dfa = compile_regex("[aé]x")
    assert _accepts(dfa, "ax")
    assert _accepts(dfa, "éx")
    assert not _accepts(dfa, b"\xc3x")


def test_escaped_nonascii_is_byte_chain():
    dfa = compile_regex("\\é!")
    assert _accepts(dfa, "é!")
    assert not _accepts(dfa, b"\xa9!")


def test_negated_class_with_multibyte_rejected():
    with pytest.raises(ValueError):
        compile_regex("[^é]")


def test_nonascii_range_endpoint_rejected():
    with pytest.raises(ValueError):
        compile_regex("[a-é]")


def test_hex_escape_range_endpoint_past_ascii_rejected():
    with pytest.raises(ValueError):
        compile_regex("[a-\\xe9]")
    # In-ASCII hex endpoints still fine.
    dfa = compile_regex("[\\x41-\\x43]")
    assert _accepts(dfa, "B")
    assert not _accepts(dfa, "D")
