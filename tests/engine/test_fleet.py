"""Elastic-fleet control loop (engine/fleet.py).

Deterministic stub-replica drills over ``FleetController``: scale-out
under pressure (with the ``fleet.scale_stall`` drill and the at-max /
budget freezes), zero-loss scale-in through the drain -> journal-migrate
ladder, wedge cycling counted on exactly the ``wedge_cycles`` rung, the
verified resurrection probe (satellite: a replica that reconnects but
fails its stats probe is NOT a resurrection), live prefill->decode pool
re-splits proven by pool-occupancy metrics, and the ``VDT_FLEET=0``
inert default. The chaos soaks at the bottom run the 2->3->1 schedule
under a seeded fault sequence with continuous traffic and a
deterministic per-session token function, so zero-loss/zero-duplication
and greedy-parity are exact assertions, not spot checks."""

import time

import pytest

from tests.conftest import make_config
from vllm_distributed_tpu.core.sched.scheduler import EngineCoreOutput
from vllm_distributed_tpu.engine import dp_client as dp_mod
from vllm_distributed_tpu.engine.core_client import (EngineCoreClient,
                                                     EngineDeadError)
from vllm_distributed_tpu.engine.dp_client import DPEngineClient
from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.request import EngineCoreRequest
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import fault_injection as fi

pytestmark = pytest.mark.faults

PROMPT = [1, 2, 3]


def _tok(session: int, pos: int) -> int:
    """Deterministic greedy token for a session's pos-th output token:
    the parity oracle. A migrated continuation resumes at the position
    its delivered prefix encodes, so any lost, duplicated, or reordered
    token breaks the exact-match assertion."""
    return 3 + (session * 131 + pos * 17) % 97


def _expected(session: int, max_tokens: int) -> list[int]:
    return [_tok(session, p) for p in range(max_tokens)]


def _session_of(rid: str) -> int:
    if rid.startswith("vdt-canary-"):
        # Canary probes are content-addressed: healthy replicas must
        # produce the SAME stream for a golden prompt in EVERY round
        # (the reference journal replays across rounds), so the stub
        # keys the token function on the prompt slot — rounds rotate
        # through the 4 golden prompts — never on the replica.
        return int(rid.split("-")[-2]) % 4
    return int(rid.split("-")[-1])


def _coords(rid: str, req: EngineCoreRequest) -> dict:
    return {"remote_req_id": rid, "pull_host": "h", "pull_port": 7,
            "num_tokens": len(req.prompt_token_ids),
            "remote_page_ids": [0]}


class _FleetStub(EngineCoreClient):
    """Scriptable replica with a deterministic token engine.

    ``engine_core`` is set so the controller's inline snapshot refresh
    polls it like an in-process engine; ``serve()`` queues one output
    batch (one next token per pending request) the way a real step
    would, computing each token from the PURE position function — a
    request re-admitted elsewhere (drain, wedge, death) resumes
    mid-stream token-identically or not at all."""

    warm_pages = 0  # scripted kv_tier warm-start page count

    def __init__(self, config) -> None:
        self.config = config
        self.engine_core = self  # inline stats refresh marker
        self.role = config.kv_transfer_config.pool_role
        self.added: list[EngineCoreRequest] = []
        self.aborted: list[str] = []
        self.outputs: list[list[EngineCoreOutput]] = []
        self.pending: dict[str, list] = {}  # rid -> [request, emitted]
        self.stats = {"num_running_reqs": 0, "num_waiting_reqs": 0,
                      "steps_dispatched": 0}
        self.dead = False
        self.fail_restart = False
        self.fail_stats = False
        self.die_consult = False  # soak: consult engine_core.die
        self.restarts = 0
        self.shutdowns = 0

    def _check(self) -> None:
        if self.dead:
            raise EngineDeadError("stub replica is dead")

    def add_request(self, request: EngineCoreRequest) -> None:
        self._check()
        self.added.append(request)
        self.pending[request.request_id] = [request, 0]

    def abort_requests(self, request_ids) -> None:
        self._check()
        self.aborted.extend(request_ids)
        for rid in request_ids:
            self.pending.pop(rid, None)

    def recv_outputs(self, timeout_ms: int):
        if self.die_consult and not self.dead:
            try:
                fi.fire_or_raise("engine_core.die")
            except fi.InjectedFault as e:
                self.dead = True
                self.pending.clear()
                self.outputs.clear()
                raise EngineDeadError(str(e)) from e
        self._check()
        return self.outputs.pop(0) if self.outputs else None

    def get_stats(self) -> dict:
        if self.fail_stats:
            raise RuntimeError("stub stats probe failed")
        s = dict(self.stats)
        s["num_running_reqs"] = len(self.pending)
        s["kv_tier"] = {"warm_start_pages": type(self).warm_pages}
        return s

    def restart(self) -> None:
        if self.fail_restart:
            raise EngineDeadError("stub replica refuses to restart")
        self.dead = False
        self.restarts += 1
        # A restarted engine is EMPTY (the balancer journal re-admits).
        self.pending.clear()
        self.outputs.clear()

    def shutdown(self) -> None:
        self.shutdowns += 1

    # -- deterministic token engine -------------------------------------
    def serve(self) -> None:
        """Queue one step's output batch for every pending request."""
        if self.dead:
            return
        self.stats["steps_dispatched"] += 1
        if not self.pending:
            return
        batch: list[EngineCoreOutput] = []
        for rid in list(self.pending):
            req, emitted = self.pending[rid]
            session = _session_of(rid)
            if self.role == "prefill":
                # Prefill-stage copy: one token, finish with the pull
                # coordinates (the handoff swallows the token and the
                # decode home regenerates the stream from position 0).
                batch.append(EngineCoreOutput(
                    req_id=rid, new_token_ids=[_tok(session, 0)],
                    finish_reason="length",
                    kv_transfer_params=_coords(rid, req)))
                self.pending.pop(rid)
                continue
            # Decode stage (or plain DP): a handoff copy carries the
            # original prompt (resume at 0); a migrated continuation's
            # prompt absorbed its delivered prefix (resume past it).
            pos = len(req.prompt_token_ids) - len(PROMPT) + emitted
            events = None
            params = req.kv_transfer_params or {}
            if (emitted == 0 and str(params.get("remote_req_id", ""))
                    .endswith("#stalled")):
                # Stalled pull coordinates: a real decode home rides
                # the retry -> local-re-prefill ladder and ships the
                # KV_PULL_LOCAL event; disagg counts the rung from it.
                events = [(time.monotonic(), ev.KV_PULL_LOCAL, None)]
            finished = emitted + 1 >= req.sampling_params.max_tokens
            batch.append(EngineCoreOutput(
                req_id=rid, new_token_ids=[_tok(session, pos)],
                finish_reason="length" if finished else None,
                events=events))
            if finished:
                self.pending.pop(rid)
            else:
                self.pending[rid][1] = emitted + 1
        if batch:
            self.outputs.append(batch)


FLEET_ENV = {
    "VDT_FLEET": "1",
    "VDT_FLEET_TICK_S": "0",      # every _tick() evaluates
    "VDT_FLEET_EVAL_TICKS": "1",  # no hysteresis unless a test wants it
    "VDT_FLEET_STALE_S": "0",     # stale freeze off unless tested
    "VDT_FLEET_WEDGE_S": "1000",  # only the drill forces a wedge
    "VDT_FLEET_DRAIN_S": "0",     # drain deadlines land immediately
    "VDT_FLEET_MIN_REPLICAS": "1",
    "VDT_FLEET_MAX_REPLICAS": "3",
    "VDT_FLEET_ACTIONS": "50",
    "VDT_FLEET_ACTION_WINDOW_S": "300",
    # Deterministic placement (live-count round-robin): the fleet tests
    # assert exact owners; the router has its own suite.
    "VDT_ROUTER": "0",
}


def make_fleet(monkeypatch, n: int = 2, **env) -> DPEngineClient:
    for key, val in {**FLEET_ENV, **env}.items():
        monkeypatch.setenv(key, val)
    config = make_config()
    config.parallel_config.data_parallel_size = n
    ft = config.fault_tolerance_config
    ft.replica_probe_interval_s = 0.01
    ft.restart_backoff_base_s = 0.0
    ft.restart_max_attempts = 100
    monkeypatch.setattr(dp_mod, "SyncMPClient", _FleetStub)
    return DPEngineClient(config, force_mp=True)


def _req(rid: str, max_tokens: int = 8) -> EngineCoreRequest:
    return EngineCoreRequest(
        request_id=rid, prompt_token_ids=list(PROMPT),
        sampling_params=SamplingParams(temperature=0.0,
                                       max_tokens=max_tokens))


def _pressure(dp, waiting: int) -> None:
    for c in dp.clients:
        c.stats["num_waiting_reqs"] = waiting


def _tick(dp, n: int = 1) -> None:
    for _ in range(n):
        dp._tick()


# ---------------------------------------------------------------------------
# Inert default
# ---------------------------------------------------------------------------
def test_fleet_off_is_inert(monkeypatch):
    """VDT_FLEET unset: no controller, the legacy resurrection probe
    owns the output path, no fleet stats entry, no fleet state."""
    monkeypatch.setenv("VDT_ROUTER", "0")
    config = make_config()
    config.parallel_config.data_parallel_size = 2
    config.fault_tolerance_config.replica_probe_interval_s = 0.01
    config.fault_tolerance_config.restart_backoff_base_s = 0.0
    monkeypatch.setattr(dp_mod, "SyncMPClient", _FleetStub)
    dp = DPEngineClient(config, force_mp=True)
    assert dp.fleet is None
    assert dp._retired == set() and dp._no_place == set()
    agg = dp._aggregate_stats([{}, {}], indices=[0, 1])
    assert "fleet" not in agg
    # Legacy probe path still resurrects (the fold is fleet-on only).
    dp.clients[0].dead = True
    dp.add_request(_req("x-0"))
    assert 0 in dp._down and dp.replica_failovers == 1
    deadline = time.monotonic() + 5.0
    while 0 in dp._down and time.monotonic() < deadline:
        time.sleep(0.02)
        dp.recv_outputs(timeout_ms=10)
    assert 0 not in dp._down
    assert dp.replica_resurrections == 1


# ---------------------------------------------------------------------------
# Scale-out (+ scale_stall / at_max / budget freezes, warm start)
# ---------------------------------------------------------------------------
def test_scale_out_under_pressure_with_scale_stall_drill(monkeypatch):
    dp = make_fleet(monkeypatch)
    monkeypatch.setattr(_FleetStub, "warm_pages", 5)
    _pressure(dp, 20)  # occupancy 40/16 >> high watermark
    fi.inject("fleet.scale_stall", max_fires=1)
    try:
        _tick(dp)
        # First attempt stalls: budget consumed, fleet intact.
        assert len(dp.clients) == 2
        assert dp.fleet.freezes.get("scale_stall") == 1
        _tick(dp)
    finally:
        fi.clear("fleet.scale_stall")
    assert len(dp.clients) == 3
    assert dp.fleet.scale_outs == 1
    stats = dp.fleet.get_stats()
    assert stats["replicas"] == 3
    # Warm start from the shared T2 namespace, counted.
    assert stats["warm_start_pages"] == 5
    # The appended replica grew the balancer state and takes traffic.
    assert len(dp._live) == 3 and len(dp._supervisors) == 3
    for i in range(3):
        dp.add_request(_req(f"r-{i}"))
    assert {dp._owner[f"r-{i}"] for i in range(3)} == {0, 1, 2}
    # Sustained pressure at the device budget: frozen at_max, not grown.
    _tick(dp)
    assert len(dp.clients) == 3
    assert dp.fleet.freezes.get("at_max", 0) >= 1


def test_budget_exhaustion_freezes_actuation(monkeypatch):
    dp = make_fleet(monkeypatch, VDT_FLEET_ACTIONS="1",
                    VDT_FLEET_MAX_REPLICAS="4")
    _pressure(dp, 20)
    _tick(dp)
    assert len(dp.clients) == 3  # first action consumed the budget
    _tick(dp)
    assert len(dp.clients) == 3
    assert dp.fleet.freezes.get("budget", 0) >= 1


def test_slo_burn_hint_scales_out_and_clears(monkeypatch):
    """PR 19: the SLO burn-rate watchdog's degraded flag reaches the
    fleet as a zero-goodput pseudo-tenant — scale-out pressure on an
    otherwise idle fleet, cleared the moment the burn subsides."""
    dp = make_fleet(monkeypatch, VDT_FLEET_SIGNALS="1",
                    VDT_FLEET_GOODPUT_FLOOR="0.5")
    fleet = dp.fleet
    # Healthy tenant above the floor + sustained burn: the hint alone
    # (occupancy is ~0) is starvation pressure.
    fleet.observe_goodput({"tenantA": 1.0}, degraded=True)
    assert fleet._goodput["_slo_burn"] == 0.0
    _pressure(dp, 0)
    _tick(dp)
    assert fleet.scale_outs == 1 and len(dp.clients) == 3
    # Burn subsides: the pseudo-tenant clears and growth stops (the
    # idle fleet returns to ordinary scale-in consideration).
    fleet.observe_goodput({"tenantA": 1.0}, degraded=False)
    assert "_slo_burn" not in fleet._goodput
    _tick(dp)
    assert fleet.scale_outs == 1


def test_stale_stats_freeze_actuation(monkeypatch):
    """A replica whose stats went quiet freezes ALL actuation (never
    reshape the fleet on blind signals); fresh stats thaw it."""
    dp = make_fleet(monkeypatch, VDT_FLEET_STALE_S="1000")
    _pressure(dp, 20)
    dp.clients[1].fail_stats = True  # its snapshot never lands
    _tick(dp, 3)
    assert len(dp.clients) == 2
    assert dp.fleet.freezes.get("stale_stats", 0) >= 1
    dp.clients[1].fail_stats = False
    _tick(dp, 2)  # snapshot lands, then actuation resumes
    assert len(dp.clients) == 3


# ---------------------------------------------------------------------------
# Scale-in: drain -> journal-migrate -> retire, zero loss
# ---------------------------------------------------------------------------
def test_scale_in_drains_and_migrates_zero_loss(monkeypatch):
    dp = make_fleet(monkeypatch, VDT_FLEET_DRAIN_S="60")
    dp.add_request(_req("s-0", max_tokens=10))
    dp.add_request(_req("s-1", max_tokens=10))
    assert dp._owner["s-0"] == 0 and dp._owner["s-1"] == 1
    # Low occupancy: (2 live + 0 waiting) / 16 < low watermark. Equal
    # load ties retire the HIGHER index: replica 1 drains.
    _tick(dp)
    assert 1 in dp._no_place and 1 in dp.fleet._draining
    assert dp.fleet._draining[1]["mode"] == "retire"
    # Draining replica leaves PLACEMENT but keeps serving.
    dp.add_request(_req("s-2", max_tokens=10))
    assert dp._owner["s-2"] == 0
    vstub = dp.clients[1]
    vstub.serve()
    delivered = dp.recv_outputs(timeout_ms=10) or []
    assert [o.req_id for o in delivered] == ["s-1"]
    assert dp._progress["s-1"] == [_tok(1, 0)]
    # Past the drain deadline: the straggler journal-migrates as a
    # token-identical continuation. No failover counted.
    dp.fleet._draining[1]["deadline"] = 0.0
    _tick(dp)
    assert 1 in dp._retired and 1 in dp._down
    assert dp.replica_failovers == 0
    assert dp.fleet.scale_ins == 1
    assert dp.fleet.get_stats()["replicas"] == 1
    assert "s-1" in vstub.aborted
    cont = next(r for r in dp.clients[0].added if r.request_id == "s-1")
    assert cont.prompt_token_ids == PROMPT + [_tok(1, 0)]
    assert cont.sampling_params.max_tokens == 9
    # Zero loss: the migrated session finishes with the exact stream.
    tokens = list(dp._progress["s-1"])
    deadline = time.monotonic() + 5.0
    while "s-1" in dp._owner and time.monotonic() < deadline:
        dp.clients[0].serve()
        for out in dp.recv_outputs(timeout_ms=10) or []:
            if out.req_id == "s-1":
                tokens.extend(out.new_token_ids)
    assert tokens == _expected(1, 10)
    # At the min-replica floor nothing more retires.
    _tick(dp, 3)
    assert dp.fleet.get_stats()["replicas"] == 1
    # Retired slots never probe (the slot is reserved for scale-out).
    time.sleep(0.05)
    _tick(dp)
    assert vstub.restarts == 0


def test_scale_out_reuses_retired_slot(monkeypatch):
    dp = make_fleet(monkeypatch)
    _tick(dp)   # retire replica 1 (occupancy 0)
    _tick(dp)   # empty drain completes immediately
    assert dp._retired == {1}
    assert dp.fleet.get_stats()["replicas"] == 1
    old_stub = dp.clients[1]
    _pressure(dp, 20)
    _tick(dp)
    # The retired slot was reused, not appended.
    assert len(dp.clients) == 2
    assert dp._retired == set() and 1 not in dp._down
    assert dp.clients[1] is not old_stub
    assert dp.fleet.scale_outs == 1


# ---------------------------------------------------------------------------
# Wedge cycling: exactly one ladder rung
# ---------------------------------------------------------------------------
def test_wedge_cycle_counts_on_exactly_one_rung(monkeypatch):
    dp = make_fleet(monkeypatch, VDT_FLEET_LOW_WATERMARK="0")
    dp.add_request(_req("w-0", max_tokens=6))
    assert dp._owner["w-0"] == 0
    vstub = dp.clients[0]
    vstub.serve()
    delivered = dp.recv_outputs(timeout_ms=10)
    assert delivered and delivered[0].new_token_ids == [_tok(0, 0)]
    fi.inject("fleet.replica_wedge", max_fires=1)
    try:
        _tick(dp)
    finally:
        fi.clear("fleet.replica_wedge")
    # The wedge rung and ONLY the wedge rung.
    assert dp.fleet.wedge_cycles == 1
    assert dp.replica_failovers == 0
    assert 0 in dp._down and 0 not in dp._retired
    assert "w-0" in vstub.aborted
    cont = next(r for r in dp.clients[1].added if r.request_id == "w-0")
    assert cont.prompt_token_ids == PROMPT + [_tok(0, 0)]
    # The folded probe force-cycles it back through the restart budget.
    deadline = time.monotonic() + 5.0
    while 0 in dp._down and time.monotonic() < deadline:
        time.sleep(0.02)
        _tick(dp)
    assert 0 not in dp._down
    assert vstub.restarts == 1
    # The in-flight session still finishes token-identically.
    tokens = list(dp._progress["w-0"])
    deadline = time.monotonic() + 5.0
    while "w-0" in dp._owner and time.monotonic() < deadline:
        dp.clients[1].serve()
        for out in dp.recv_outputs(timeout_ms=10) or []:
            if out.req_id == "w-0":
                tokens.extend(out.new_token_ids)
    assert tokens == _expected(0, 6)


# ---------------------------------------------------------------------------
# Verified resurrection (satellite accounting fix)
# ---------------------------------------------------------------------------
def test_resurrection_not_counted_until_health_verified(monkeypatch):
    dp = make_fleet(monkeypatch)
    dp.clients[0].dead = True
    dp.add_request(_req("x-0"))  # discovers the death, fails over
    assert 0 in dp._down and dp.replica_failovers == 1
    # The probe reconnects (restart succeeds) but the replica cannot
    # answer its stats probe: NOT a resurrection, still down.
    dp.clients[0].fail_stats = True
    deadline = time.monotonic() + 5.0
    while dp.clients[0].restarts == 0 and time.monotonic() < deadline:
        time.sleep(0.02)
        _tick(dp)
    time.sleep(0.05)
    _tick(dp)
    assert dp.clients[0].restarts >= 1
    assert 0 in dp._down
    assert dp.replica_resurrections == 0
    # Health restored: the next probe verifies and counts exactly once.
    dp.clients[0].fail_stats = False
    deadline = time.monotonic() + 5.0
    while 0 in dp._down and time.monotonic() < deadline:
        time.sleep(0.02)
        _tick(dp)
    assert 0 not in dp._down
    assert dp.replica_resurrections == 1


# ---------------------------------------------------------------------------
# Live pool re-split
# ---------------------------------------------------------------------------
@pytest.fixture
def disagg_fleet(monkeypatch):
    monkeypatch.setenv("VDT_DISAGG", "1")
    monkeypatch.setenv("VDT_DISAGG_PREFILL_REPLICAS", "2")
    return make_fleet(monkeypatch, n=3,
                      VDT_FLEET_MIN_REPLICAS="2",
                      VDT_FLEET_HIGH_WATERMARK="100",
                      VDT_FLEET_LOW_WATERMARK="0.1")


def test_live_resplit_converts_prefill_to_decode(disagg_fleet):
    dp = disagg_fleet
    assert dp.disagg.prefill_pool == [0, 1]
    assert dp.disagg.decode_pool == [2]
    # In-flight prefill-stage work on one pool member.
    dp.add_request(_req("c-0", max_tokens=6))
    victim = dp._owner["c-0"]
    assert victim in (0, 1)
    other = 1 - victim
    occ_before = dp.disagg.get_stats(dp.request_counts())
    assert occ_before["pool_occupancy"]["prefill"] == 1
    # Decode pool pressured: occupancy 20/8 >> prefill * ratio.
    dp.clients[2].stats["num_waiting_reqs"] = 20
    _tick(dp)
    # The convert victim is the LEAST-LOADED donor — the prefill
    # replica without the live request.
    assert other in dp.fleet._draining
    assert dp.fleet._draining[other]["mode"] == "convert"
    _tick(dp)  # drain (no live work) completes -> rebuild as decode
    assert dp.disagg.prefill_pool == [victim]
    assert sorted(dp.disagg.decode_pool) == sorted([other, 2])
    assert dp.disagg.resplits == 1
    assert dp.fleet.get_stats()["resplits"] == 1
    # Role-appropriate respawn: the new engine is a consumer.
    rc = dp.clients[other].config
    assert rc.kv_transfer_config.kv_role == "kv_consumer"
    assert rc.kv_transfer_config.pool_role == "decode"
    # The in-flight prefill-stage request survived on the old pool.
    assert dp._owner["c-0"] == victim
    assert dp.replica_failovers == 0
    occ_after = dp.disagg.get_stats(dp.request_counts())
    assert occ_after["pools"] == {"prefill": [victim],
                                  "decode": sorted([other, 2])}
    assert occ_after["pool_occupancy"]["prefill"] == 1


def test_resplit_drains_in_flight_work_to_pool_peer(disagg_fleet):
    """A convert victim still holding prefill-stage work past the
    drain deadline journal-migrates it to the surviving prefill
    member as a fresh stage copy — nothing dropped, no death rung."""
    dp = disagg_fleet
    dp.add_request(_req("c-0", max_tokens=6))
    dp.add_request(_req("c-1", max_tokens=6))
    assert {dp._owner["c-0"], dp._owner["c-1"]} == {0, 1}
    dp.clients[2].stats["num_waiting_reqs"] = 20
    _tick(dp)
    # Equal donor load: ties convert the higher index.
    assert 1 in dp.fleet._draining
    moved = next(rid for rid in ("c-0", "c-1") if dp._owner[rid] == 1)
    _tick(dp)  # past the (zero-second) deadline: migrate + rebuild
    assert dp.disagg.prefill_pool == [0]
    assert dp._owner[moved] == 0
    copies = [r for r in dp.clients[0].added if r.request_id == moved]
    # Re-admitted as a fresh one-token prefill-stage copy.
    assert copies[-1].sampling_params.max_tokens == 1
    assert dp.replica_failovers == 0
    assert dp.disagg.fallbacks.get("prefill_death", 0) == 0


def test_asymmetric_role_tp_freezes_resplit(disagg_fleet, monkeypatch):
    dp = disagg_fleet
    monkeypatch.setattr(dp.disagg, "symmetric_roles", lambda: False)
    dp.clients[2].stats["num_waiting_reqs"] = 20
    _tick(dp, 2)
    assert dp.fleet._draining == {}
    assert dp.disagg.resplits == 0
    assert dp.fleet.freezes.get("asym_tp", 0) >= 1


# ---------------------------------------------------------------------------
# Stats aggregation + prometheus rendering + timeline events
# ---------------------------------------------------------------------------
def test_fleet_stats_aggregate_and_render(monkeypatch):
    dp = make_fleet(monkeypatch, VDT_ROUTER="1")  # scale grows the router
    _pressure(dp, 20)
    _tick(dp, 2)  # scale out to 3, then freeze at_max
    assert len(dp.clients) == 3
    agg = dp._aggregate_stats([{}, {}, {}], indices=[0, 1, 2])
    assert agg["fleet"]["replicas"] == 3
    assert agg["fleet"]["scale_outs"] == 1
    assert agg["fleet"]["freezes"].get("at_max", 0) >= 1
    # The scale-out landed on the shared timeline.
    assert any(e[2] == ev.FLEET_SCALE_OUT
               for e in agg.get("timeline_events", []))
    from vllm_distributed_tpu.metrics.prometheus import render_metrics
    text = render_metrics(agg)
    assert "vdt:fleet_replicas 3" in text
    assert "vdt:fleet_scale_outs_total 1" in text
    assert 'vdt:fleet_freezes_total{reason="at_max"}' in text


# ---------------------------------------------------------------------------
# Chaos soaks: 2 -> 3 -> 1 under a seeded fault sequence
# ---------------------------------------------------------------------------
class _Collector:
    """Delivered-output ledger: the zero-loss / zero-duplication and
    parity oracle. finished counts must end at exactly 1 per session."""

    def __init__(self) -> None:
        self.tokens: dict[str, list[int]] = {}
        self.finishes: dict[str, int] = {}

    def take(self, outs) -> None:
        for out in outs or []:
            self.tokens.setdefault(out.req_id, []).extend(
                out.new_token_ids)
            if out.finished:
                self.finishes[out.req_id] = \
                    self.finishes.get(out.req_id, 0) + 1

    def assert_exact(self, rid: str, max_tokens: int) -> None:
        assert self.finishes.get(rid) == 1, \
            f"{rid}: finished {self.finishes.get(rid, 0)} times"
        assert self.tokens[rid] == _expected(_session_of(rid),
                                             max_tokens), rid


def _pump(dp, collector) -> None:
    for c in dp.clients:
        if isinstance(c, _FleetStub):
            c.serve()
    collector.take(dp.recv_outputs(timeout_ms=10))
    time.sleep(0.001)


def _drive_until(dp, collector, done, timeout_s: float = 10.0) -> None:
    deadline = time.monotonic() + timeout_s
    while not done() and time.monotonic() < deadline:
        _pump(dp, collector)
    assert done(), "soak phase did not converge"


def test_chaos_mini_soak_scale_schedule(monkeypatch):
    """Tier-1 soak: the full 2 -> 3 -> 1 replica schedule with
    continuous traffic and the fleet drills armed (scale_stall on the
    way up, replica_wedge mid-flight), every session token-exact."""
    dp = make_fleet(monkeypatch)
    col = _Collector()
    n_sessions, mt = 8, 6
    for i in range(n_sessions):
        dp.add_request(_req(f"s-{i}", max_tokens=mt))
    # Surge: pressure 2 -> 3 through one provisioning stall.
    _pressure(dp, 20)
    fi.inject("fleet.scale_stall", max_fires=1)
    fi.inject("fleet.replica_wedge", max_fires=1)
    try:
        _drive_until(dp, col, lambda: len(dp.clients) == 3)
        _drive_until(dp, col, lambda: dp.fleet.wedge_cycles == 1,
                     timeout_s=5.0)
        _drive_until(dp, col, lambda: len(col.finishes) == n_sessions)
    finally:
        fi.clear("fleet.scale_stall")
        fi.clear("fleet.replica_wedge")
    assert dp.fleet.freezes.get("scale_stall") == 1
    assert dp.fleet.scale_outs == 1
    # One rung each: the wedge never counted as a failover.
    assert dp.fleet.wedge_cycles == 1
    assert dp.replica_failovers == 0
    # Quiesce: the fleet walks down to the min-replica floor.
    _pressure(dp, 0)
    _drive_until(dp, col,
                 lambda: dp.fleet.get_stats()["replicas"] == 1,
                 timeout_s=10.0)
    assert dp.fleet.scale_ins >= 2
    # Zero lost, zero duplicated, token-exact — every session.
    for i in range(n_sessions):
        col.assert_exact(f"s-{i}", mt)


@pytest.mark.slow
def test_chaos_soak_seeded_faults(monkeypatch):
    """Heaviest soak, two stages under seeded faults with continuous
    traffic. Stage 1 (disaggregated 1P+1D fleet): ``engine_core.die``
    and ``disagg.handoff_stall`` fire mid-stream while pressure scales
    the fleet to 3. Stage 2 (plain DP fleet): the full 2 -> 3 -> 1
    schedule with ``engine_core.die`` + ``fleet.replica_wedge``. Every
    degradation lands on exactly one ladder rung and every session is
    token-exact with zero lost/duplicated requests."""
    # ---- Stage 1: disagg fleet, handoff_stall then die ----
    monkeypatch.setenv("VDT_DISAGG", "1")
    dp = make_fleet(monkeypatch, n=2, VDT_FLEET_MIN_REPLICAS="2")
    assert dp.disagg.prefill_pool == [0]
    assert dp.disagg.decode_pool == [1]
    col = _Collector()
    mt = 6
    fi.inject("disagg.handoff_stall", max_fires=2)
    try:
        for i in range(6):
            dp.add_request(_req(f"s-{i}", max_tokens=mt))
        # Both stalled handoffs degrade to local re-prefill and the
        # first wave completes before the deaths start.
        _drive_until(dp, col, lambda: len(col.finishes) == 6,
                     timeout_s=20.0)
        assert dp.disagg.fallbacks.get("local_reprefill", 0) == 2
        # Surge: scale to 3 (grows the pressured pool).
        _pressure(dp, 20)
        _drive_until(dp, col, lambda: len(dp.clients) == 3)
        # Now seed the death: the consult rides the output poll
        # exactly like the engine-core busy loop's.
        fi.inject("engine_core.die", max_fires=1)
        for c in dp.clients:
            c.die_consult = True
        for i in range(6, 12):
            dp.add_request(_req(f"s-{i}", max_tokens=mt))
        _drive_until(dp, col, lambda: dp.replica_failovers >= 1,
                     timeout_s=5.0)
        _drive_until(dp, col, lambda: len(col.finishes) == 12,
                     timeout_s=20.0)
    finally:
        fi.clear("disagg.handoff_stall")
        fi.clear("engine_core.die")
    # One rung each: the stalled handoffs degraded to local re-prefill
    # (not a death), the death counted one failover (not a wedge).
    assert dp.disagg.fallbacks.get("local_reprefill", 0) == 2
    assert dp.replica_failovers == 1
    assert dp.fleet.wedge_cycles == 0
    assert dp.fleet.scale_outs >= 1
    for i in range(12):
        col.assert_exact(f"s-{i}", mt)

    # ---- Stage 2: plain DP fleet, 2 -> 3 -> 1 with die + wedge ----
    monkeypatch.setenv("VDT_DISAGG", "0")
    dp2 = make_fleet(monkeypatch, n=2)
    col2 = _Collector()
    for i in range(8):
        dp2.add_request(_req(f"s-{i}", max_tokens=mt))
    _pressure(dp2, 20)
    fi.inject("fleet.replica_wedge", max_fires=1)
    fi.inject("engine_core.die", max_fires=1)
    try:
        _drive_until(dp2, col2, lambda: len(dp2.clients) == 3)
        for c in dp2.clients:
            c.die_consult = True
        _drive_until(dp2, col2,
                     lambda: (dp2.fleet.wedge_cycles == 1
                              and dp2.replica_failovers >= 1),
                     timeout_s=10.0)
        _drive_until(dp2, col2, lambda: len(col2.finishes) == 8,
                     timeout_s=20.0)
    finally:
        fi.clear("fleet.replica_wedge")
        fi.clear("engine_core.die")
    assert dp2.fleet.wedge_cycles == 1
    assert dp2.replica_failovers == 1
    _pressure(dp2, 0)
    _drive_until(dp2, col2,
                 lambda: dp2.fleet.get_stats()["replicas"] == 1,
                 timeout_s=10.0)
    assert dp2.fleet.scale_ins >= 2
    for i in range(8):
        col2.assert_exact(f"s-{i}", mt)


# ---------------------------------------------------------------------------
# Correctness sentinel (ISSUE 20): canary probes -> suspicion ->
# fleet quarantine, the numerics feed, and the inert default.
# ---------------------------------------------------------------------------
def _drive_canary_rounds(dp, n: int, timeout_s: float = 5.0) -> None:
    """Serve every live stub and pump the MP receive path until ``n``
    more canary rounds have resolved (recv_outputs's tick injects due
    probes; absorption resolves the round)."""
    plane = dp.correctness
    target = plane._round_idx + n
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        for c in dp.clients:
            if not getattr(c, "dead", False):
                c.serve()
        dp.recv_outputs(timeout_ms=10)
        if plane._round_idx >= target and plane._round is None:
            return
    raise AssertionError(f"canary rounds did not resolve "
                         f"(idx={plane._round_idx}, want {target})")


def _canary_env(**extra) -> dict:
    # Pin min replicas so the idle autoscaler can't scale-in a healthy
    # replica mid-drill (canaries carry no schedulable load).
    env = {"VDT_CORRECTNESS": "1", "VDT_CANARY_INTERVAL_S": "0",
           "VDT_CANARY_QUARANTINE_N": "2", "VDT_FLEET_SIGNALS": "1",
           "VDT_NUMERICS_DRIFT_FRAC": "0.5",
           "VDT_FLEET_MIN_REPLICAS": "8"}
    env.update(extra)
    return env


def test_correctness_off_is_inert(monkeypatch):
    """VDT_CORRECTNESS unset (the default): no plane object, no canary
    traffic, no correctness/numerics stats keys — the revert pin."""
    dp = make_fleet(monkeypatch)
    assert dp.correctness is None
    dp.add_request(_req("s-0"))
    for _ in range(10):
        for c in dp.clients:
            c.serve()
        dp.recv_outputs(timeout_ms=10)
    for c in dp.clients:
        assert not any(r.request_id.startswith("vdt-canary-")
                       for r in c.added)
    agg = dp._aggregate_stats([{}, {}], indices=[0, 1])
    assert "correctness" not in agg and "numerics" not in agg


def test_canary_clean_rounds_self_seed_and_stay_quiet(monkeypatch):
    """Healthy fleet: the first round per golden prompt self-seeds the
    reference journal, every later round scores clean — zero
    divergences, zero suspects (the false-positive budget is zero)."""
    dp = make_fleet(monkeypatch, **_canary_env())
    plane = dp.correctness
    assert plane is not None
    _drive_canary_rounds(dp, 8)
    stats = plane.get_stats()
    assert sum(stats["probes"].values()) >= 16
    assert stats["divergences"] == {}
    assert plane.suspects() == {}
    assert stats["journal_entries"] == 4  # one per golden prompt
    assert stats["quarantine_hints"] == 0
    # Canaries never leaked into tenant bookkeeping.
    assert not dp._requests and not dp._progress


def test_canary_flip_token_detection_to_quarantine(monkeypatch):
    """The e2e drill: ``canary.flip_token`` perturbs replica 1's canary
    stream -> divergence within the first corrupted probe (<= 3 probe
    acceptance bound) -> suspect gauge isolates replica 1 only -> a
    second strike emits the quarantine hint -> the fleet controller
    force-cycles the replica through the shared wedge rung."""
    dp = make_fleet(monkeypatch, **_canary_env())
    plane = dp.correctness
    # Four clean rounds seed the journal for every golden prompt (a
    # 2-replica tie needs the reference as tiebreaker).
    _drive_canary_rounds(dp, 4)
    assert plane.divergences == {}
    p0 = plane.probes.get(1, 0)
    # Absorb order interleaves r0,r1 per cycle: rate 0.5 fires on every
    # 2nd delta — always replica 1.
    fi.inject("canary.flip_token", rate=0.5)
    try:
        _drive_canary_rounds(dp, 1)
        assert plane.probes.get(1, 0) - p0 <= 3  # detection bound
        assert sum(plane.divergences.get(1, {}).values()) >= 1
        assert plane.suspects() == {1: 1}
        assert plane.quarantine_hints_emitted == 0  # one strike so far
        _drive_canary_rounds(dp, 1)  # second strike
    finally:
        fi.clear("canary.flip_token")
    assert plane.quarantine_hints_emitted == 1
    assert dp.fleet.quarantines == 0  # hint pending, not yet forwarded
    _tick(dp)  # forwards the hint; fleet.tick() actuates it
    assert dp.fleet.quarantines == 1
    assert dp.fleet.get_stats()["quarantines"] == 1
    assert 1 in dp._down
    # Replica 0 was never suspected and keeps serving.
    assert 0 not in dp._down
    # The cycled slot's suspicion history died with it.
    assert plane.suspects() == {}
    # The sentinel actuated through the shared rung: no failover, no
    # wedge counted.
    assert dp.replica_failovers == 0
    assert dp.fleet.wedge_cycles == 0


def test_canary_vote_isolates_minority_on_three_replicas(monkeypatch):
    """With >= 3 replicas the cross-replica vote alone dates the odd
    one out (cause ``vote``) — no journal reference needed: corruption
    older than the journal cannot hide."""
    dp = make_fleet(monkeypatch, n=3, **_canary_env())
    plane = dp.correctness
    # Absorb order interleaves r0,r1,r2: rate 1/3 fires on every 3rd
    # delta — always replica 2. No clean round first: the vote must
    # work with an unseeded journal.
    fi.inject("canary.flip_token", rate=1 / 3)
    try:
        _drive_canary_rounds(dp, 1)
    finally:
        fi.clear("canary.flip_token")
    assert plane.divergences.get(2, {}).get("vote", 0) >= 1
    assert plane.suspects() == {2: 1}
    # The corrupted round never seeded the journal (not unanimous).
    assert plane.get_stats()["journal_entries"] == 0


def test_numerics_nan_inject_feeds_quarantine(monkeypatch):
    """``numerics.nan_inject`` poisons one replica's tap harvest; the
    nan_steps delta rides the DP stats merge into the suspicion ladder
    and (quarantine_n=1) straight to a fleet quarantine hint."""
    import numpy as np

    from vllm_distributed_tpu.correctness_plane import NumericsTap
    dp = make_fleet(monkeypatch, **_canary_env(
        VDT_CANARY_INTERVAL_S="1000", VDT_CANARY_QUARANTINE_N="1"))
    plane = dp.correctness
    tap = NumericsTap()
    clean = np.array([0.0, 1.0, 2.0], dtype=np.float32)
    fi.inject("numerics.nan_inject", rate=1.0, max_fires=1)
    try:
        tap.dispatch(clean)
        tap.dispatch(clean)  # harvests the poisoned previous step
    finally:
        fi.clear("numerics.nan_inject")
    bad = tap.stats()
    assert bad["nan_steps"] == 1
    healthy = {"nan_steps": 0, "entropy_window_mean": 1.0,
               "window_steps": 4}
    agg = dp._aggregate_stats(
        [{"numerics": healthy}, {"numerics": bad}], indices=[0, 1])
    # Per-replica numerics maps merge keyed by replica, never summed.
    assert set(agg["numerics"]) == {0, 1}
    assert agg["numerics"][1]["nan_steps"] == 1
    assert plane.suspects() == {1: 1}
    assert plane.divergences[1] == {"nan_logits": 1}
    assert plane.quarantine_hints_emitted == 1
    _tick(dp)
    assert dp.fleet.quarantines == 1
    assert 1 in dp._down and 0 not in dp._down


def test_quarantine_hint_without_signals_is_dropped(monkeypatch):
    """VDT_FLEET_SIGNALS=0: the sentinel still detects and raises the
    suspect gauge, but the fleet never actuates — hints are a gated
    SIGNAL, not a new actuation path."""
    dp = make_fleet(monkeypatch, **_canary_env(VDT_FLEET_SIGNALS="0"))
    plane = dp.correctness
    _drive_canary_rounds(dp, 4)  # seed every golden prompt
    fi.inject("canary.flip_token", rate=0.5)
    try:
        _drive_canary_rounds(dp, 2)
    finally:
        fi.clear("canary.flip_token")
    assert plane.suspects() == {1: 1}
    assert plane.quarantine_hints_emitted == 1
    _tick(dp, 3)
    assert dp.fleet.quarantines == 0
    assert 1 not in dp._down
