"""Disaggregated prefill/decode serving tier (engine/disagg.py).

E2e two-pool fleets (in-process replicas on the virtual CPU mesh)
pinning the acceptance criteria: greedy token parity with the
monolithic balancer, the handoff recovery ladder (stalled pull ->
local re-prefill on the decode home; prefill death mid-handoff ->
re-admission) with its fallback counters, per-role precompile-lattice
pruning, asymmetric TP=1 prefill -> TP=2 TPLA decode handoff
bit-exactness, and the VDT_DISAGG=0 wholesale revert. Deterministic
stub-replica drills cover the interception state machine itself."""

import time

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.core.sched.scheduler import EngineCoreOutput
from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.dp_client import DPEngineClient
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.request import EngineCoreRequest
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import fault_injection as fi


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64, eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_disagg")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


PROMPTS = [
    [3, 17, 92, 45, 8, 21, 33, 64, 90],                # 2 full pages
    [5, 9, 33, 71, 14, 62, 77, 80, 6, 41, 93, 2, 54],  # 3 full pages
    [11, 12, 13, 14, 15, 16],
    [7, 7, 7, 21],                                     # 1 full page
]


def run(engine, tag, prompts=None, max_tokens=6, max_iters=20000):
    """Drive the engine to completion; the disagg pull threads need
    GIL slots, hence the tiny sleep."""
    prompts = PROMPTS if prompts is None else prompts
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                        ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"{tag}-{i}", list(p), sp)
    done = {}
    for _ in range(max_iters):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
        time.sleep(0.001)
    assert not engine.has_unfinished_requests(), \
        f"{tag}: finished only {sorted(done)}"
    return [done[f"{tag}-{i}"].outputs[0].token_ids
            for i in range(len(prompts))]


@pytest.fixture
def disagg_env(monkeypatch):
    monkeypatch.setenv("VDT_DISAGG", "1")
    yield monkeypatch


@pytest.fixture(scope="module")
def monolithic_tokens(checkpoint):
    """Greedy outputs of the monolithic 2-replica balancer — the parity
    reference every disagg fleet must reproduce token-identically."""
    import os
    assert os.environ.get("VDT_DISAGG", "0") == "0"
    engine = make_engine(checkpoint, data_parallel_size=2)
    assert engine.engine_core.disagg is None  # VDT_DISAGG=0 revert
    toks = run(engine, "mono")
    engine.shutdown()
    return toks


# ---------------------------------------------------------------------------
# E2e: two-pool fleet parity + handoff accounting + decode-home
# residency registration (the router bugfix).
# ---------------------------------------------------------------------------
def test_two_pool_fleet_token_parity_and_handoff_accounting(
        checkpoint, monolithic_tokens, disagg_env):
    engine = make_engine(checkpoint, data_parallel_size=2)
    client: DPEngineClient = engine.engine_core
    assert client.disagg is not None
    assert client.disagg.prefill_pool == [0]
    assert client.disagg.decode_pool == [1]
    # Per-role replica configs: producer/consumer split, decode token
    # budget capped (deep decode batches, small compiled ladder).
    rc0 = client.clients[0].config
    rc1 = client.clients[1].config
    assert rc0.kv_transfer_config.kv_role == "kv_producer"
    assert rc0.kv_transfer_config.pool_role == "prefill"
    assert rc1.kv_transfer_config.kv_role == "kv_consumer"
    assert rc1.kv_transfer_config.pool_role == "decode"
    assert (rc1.scheduler_config.max_num_batched_tokens
            < rc0.scheduler_config.max_num_batched_tokens)

    got = run(engine, "dis")
    assert got == monolithic_tokens  # placement must never change tokens

    stats = engine.get_stats()
    d = stats["disagg"]
    assert d["handoffs"] == len(PROMPTS)
    assert d["handoff_seconds"]["count"] == len(PROMPTS)
    assert d["pool_occupancy"] == {"prefill": 0, "decode": 0}
    # No recovery rung fired on the happy path.
    assert d["fallbacks"].get("local_reprefill", 0) == 0
    assert stats.get("kv_pull_failures", 0) == 0

    # Decode-home residency registration (the on_finish bugfix): the
    # finished sequences' pages live on the DECODE home, so the full
    # prompt+generated page chain must score higher affinity there
    # than on the admitting prefill replica (which only ever held the
    # prompt pages, and whose pages left with the pull).
    router = client.router
    full = list(PROMPTS[1]) + list(got[1])
    hashes = router._page_hashes(full)
    assert router._affinity(1, hashes) > router._affinity(0, hashes)

    # /metrics rendering of the new families.
    from vllm_distributed_tpu.metrics.prometheus import render_metrics
    text = render_metrics(stats)
    assert f"vdt:disagg_handoffs_total {len(PROMPTS)}" in text
    assert 'vdt:pool_occupancy{pool="decode"} 0' in text
    assert "vdt:disagg_handoff_seconds_count" in text
    engine.shutdown()


def test_disagg_off_reverts_to_monolithic_balancer(monkeypatch):
    """VDT_DISAGG=0 (the default): no coordinator, no pool configs, no
    connector — byte-identical to the pre-disagg balancer. (The
    monolithic_tokens fixture additionally proves it on a real fleet;
    this covers the config surface on the cheap stub transport.)"""
    from tests.conftest import make_config
    from vllm_distributed_tpu.engine import dp_client as dp_mod
    monkeypatch.setenv("VDT_DISAGG", "0")
    config = make_config()
    config.parallel_config.data_parallel_size = 2
    monkeypatch.setattr(dp_mod, "SyncMPClient", _StubReplica)
    client = DPEngineClient(config, force_mp=True)
    assert client.disagg is None
    for c in client.clients:
        assert c.config.kv_transfer_config.kv_connector is None
        assert c.config.kv_transfer_config.pool_role is None
    assert "disagg" not in client._aggregate_stats([{}, {}],
                                                   indices=[0, 1])


# ---------------------------------------------------------------------------
# Recovery drills
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("timeline", ["1", "0"])
def test_handoff_stall_degrades_to_local_reprefill(
        checkpoint, monolithic_tokens, disagg_env, timeline):
    """disagg.handoff_stall breaks every handoff's pull coordinates:
    the decode home must ride the scheduler ladder (bounded retries ->
    local re-prefill) to token-identical output, with the fallback
    counted — including with the request-timeline recorder OFF (the
    recovery-ladder accounting must not ride a telemetry kill switch:
    the scheduler force-ships KV_PULL_RETRY/KV_PULL_LOCAL events)."""
    disagg_env.setenv("VDT_REQUEST_TIMELINE", timeline)
    engine = make_engine(checkpoint, data_parallel_size=2,
                         kv_pull_timeout_s=1.0)
    fi.inject("disagg.handoff_stall")
    try:
        got = run(engine, "stall", prompts=PROMPTS[:2])
    finally:
        fi.clear("disagg.handoff_stall")
    assert got == monolithic_tokens[:2]
    d = engine.get_stats()["disagg"]
    # Every multi-page handoff degraded to a local re-prefill on its
    # decode home (single-page-or-less prompts may resolve through
    # no_pull_coords instead of a failed pull).
    assert d["fallbacks"].get("local_reprefill", 0) >= 2
    engine.shutdown()


def test_prefill_death_mid_handoff_readmits(checkpoint, monolithic_tokens,
                                            disagg_env):
    """A prefill replica dying with prefill-stage requests in flight:
    the failover path re-admits them as fresh prefill-stage copies on
    the surviving prefill pool, counted as prefill_death fallbacks,
    and greedy output is unchanged."""
    from vllm_distributed_tpu.engine.core_client import EngineDeadError
    disagg_env.setenv("VDT_DISAGG_PREFILL_REPLICAS", "2")
    engine = make_engine(checkpoint, data_parallel_size=3)
    client: DPEngineClient = engine.engine_core
    assert client.disagg.prefill_pool == [0, 1]
    assert client.disagg.decode_pool == [2]

    prompts = PROMPTS[:2]
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"pd-{i}", list(p), sp)
    owners = {client._owner[f"pd-{i}"] for i in range(len(prompts))}
    assert owners <= {0, 1}  # everything admitted to the prefill pool
    victim = min(owners)

    class _DeadProxy:
        """Every call answers EngineDeadError, like a crashed core."""

        def __getattr__(self, name):
            def _boom(*a, **k):
                raise EngineDeadError("killed by test")
            return _boom

    alive_client = client.clients[victim]
    client.clients[victim] = _DeadProxy()
    try:
        done = {}
        for _ in range(20000):
            for out in engine.step():
                if out.finished:
                    done[out.request_id] = out
            if not engine.has_unfinished_requests():
                break
            time.sleep(0.001)
        assert len(done) == len(prompts)
        got = [done[f"pd-{i}"].outputs[0].token_ids
               for i in range(len(prompts))]
        assert got == monolithic_tokens[:2]
        stats = engine.get_stats()
        assert stats["disagg"]["fallbacks"].get("prefill_death", 0) >= 1
        assert stats["replica_failovers"] == 1
        assert victim in client._down
    finally:
        client.clients[victim] = alive_client
        engine.shutdown()


@pytest.mark.slow
def test_disagg_over_shared_storage_connector(checkpoint,
                                              monolithic_tokens,
                                              disagg_env, tmp_path):
    """A parent config that pins SharedStorageConnector keeps it: the
    handoff then rides content-hash page files instead of a pull (no
    kv_transfer_params at all), and parity still holds — the 'existing
    connectors' contract covers all three transports."""
    engine = make_engine(
        checkpoint, data_parallel_size=2,
        kv_connector="SharedStorageConnector",
        kv_connector_extra_config={
            "shared_storage_path": str(tmp_path)})
    client = engine.engine_core
    assert (client.clients[0].config.kv_transfer_config.kv_connector
            == "SharedStorageConnector")
    got = run(engine, "ss")
    assert got == monolithic_tokens
    d = engine.get_stats()["disagg"]
    assert d["handoffs"] == len(PROMPTS)
    # Hash-addressed handoffs carry no pull coordinates by design —
    # that is not a fallback.
    assert d["fallbacks"].get("no_pull_coords", 0) == 0
    # The prefill pool really produced page files for the store.
    assert any(tmp_path.iterdir())
    engine.shutdown()


# ---------------------------------------------------------------------------
# Per-role precompile pruning: each pool warms a strict subset of the
# monolithic lattice.
# ---------------------------------------------------------------------------
@pytest.mark.slow  # three engines with full CPU precompile warm-ups
def test_pool_precompile_lattices_are_strict_subsets(checkpoint,
                                                     disagg_env):
    disagg_env.setenv("VDT_PRECOMPILE", "1")
    kw = dict(max_num_batched_tokens=32, num_scheduler_steps=2)

    disagg_env.setenv("VDT_DISAGG", "0")
    mono = make_engine(checkpoint, **kw)
    mono_graphs = int(mono.get_stats()["precompile_graphs"])
    mono.shutdown()

    disagg_env.setenv("VDT_DISAGG", "1")
    fleet = make_engine(checkpoint, data_parallel_size=2, **kw)
    per = fleet.get_stats()["dp_replicas"]
    prefill_graphs = int(per[0]["precompile_graphs"])
    decode_graphs = int(per[1]["precompile_graphs"])
    fleet.shutdown()

    # Each pool's warmed lattice is a strict subset of the monolithic
    # one: the prefill pool drops the decode-burst (multi-step) and
    # fused-block variants; the decode pool additionally shrinks the
    # token-bucket ladder to its capped budget and skips the
    # prompt-logprob graphs.
    assert 0 < prefill_graphs < mono_graphs
    assert 0 < decode_graphs < prefill_graphs


# ---------------------------------------------------------------------------
# Asymmetric meshes: TP=1 prefill producer -> TP=2 TPLA decode
# consumer over the same handoff params a disagg fleet ships.
# ---------------------------------------------------------------------------
@pytest.mark.slow  # three MLA engines incl. a TP=2 mesh
def test_asymmetric_tp1_prefill_to_tp2_tpla_decode_bit_exact():
    from tests.models.test_tpla import make_config
    from vllm_distributed_tpu.config import KVTransferConfig

    def engine(tp, role=None, tpla=True):
        cfg = make_config(tp=tp, tpla=tpla)
        if role is not None:
            cfg.kv_transfer_config = KVTransferConfig(
                kv_connector="DCNPullConnector", kv_role=role,
                kv_connector_extra_config={"pull_port": 0})
        return LLMEngine(cfg, load_tokenizer=False)

    prompts = [[3, 17, 92, 45, 8, 21, 33, 64, 90],
               [5, 9, 33, 71, 14, 62, 77, 80, 6]]
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)

    baseline = run(engine(tp=1), "base", prompts=prompts)

    producer = engine(tp=1, role="kv_producer", tpla=False)
    for i, p in enumerate(prompts):
        producer.add_request(
            f"prod-{i}", list(p),
            SamplingParams(temperature=0.0, max_tokens=1,
                           ignore_eos=True))
    params = {}
    for _ in range(500):
        for out in producer.step():
            if out.finished:
                params[out.request_id] = out.kv_transfer_params
        if not producer.has_unfinished_requests():
            break
    assert all(params.get(f"prod-{i}") for i in range(len(prompts)))

    consumer = engine(tp=2, role="kv_consumer")
    runner = (consumer.engine_core.engine_core.executor
              .worker.model_runner)
    assert runner.model.tpla_shards == 2  # latent cache TP-sharded
    for i, p in enumerate(prompts):
        consumer.add_request(f"cons-{i}", list(p), sp,
                             kv_transfer_params=params[f"prod-{i}"])
    done = {}
    for _ in range(20000):
        for out in consumer.step():
            if out.finished:
                done[out.request_id] = out
        producer.step()  # serve the pulls
        if len(done) == len(prompts):
            break
        time.sleep(0.001)
    got = [done[f"cons-{i}"].outputs[0].token_ids
           for i in range(len(prompts))]
    assert got == baseline  # bit-exact across the TP-degree change
    # The latent pages really were pulled, not recomputed.
    assert all(done[f"cons-{i}"].num_cached_tokens > 0
               for i in range(len(prompts)))
    producer.shutdown()
    consumer.shutdown()


# ---------------------------------------------------------------------------
# Deterministic stub drills over the interception state machine.
# ---------------------------------------------------------------------------
class _StubReplica:
    def __init__(self, config) -> None:
        self.config = config
        self.added: list[EngineCoreRequest] = []

    def add_request(self, request: EngineCoreRequest) -> None:
        self.added.append(request)

    def abort_requests(self, request_ids) -> None:
        pass

    def recv_outputs(self, timeout_ms: int):
        return None

    def shutdown(self) -> None:
        pass


@pytest.fixture
def stub_fleet(monkeypatch):
    from tests.conftest import make_config
    from vllm_distributed_tpu.engine import dp_client as dp_mod
    monkeypatch.setenv("VDT_DISAGG", "1")
    config = make_config()
    config.parallel_config.data_parallel_size = 2
    monkeypatch.setattr(dp_mod, "SyncMPClient", _StubReplica)
    return DPEngineClient(config, force_mp=True)


def _req(rid, max_tokens=8):
    return EngineCoreRequest(
        request_id=rid, prompt_token_ids=[1, 2, 3],
        sampling_params=SamplingParams(temperature=0.0,
                                       max_tokens=max_tokens))


def test_stub_handoff_moves_request_with_pull_params(stub_fleet):
    dp = stub_fleet
    dp.add_request(_req("a"))
    # Admitted to the prefill pool as a one-token prefill-stage copy.
    assert dp._owner["a"] == 0
    (staged, ) = dp.clients[0].added
    assert staged.sampling_params.max_tokens == 1
    assert dp._requests["a"].sampling_params.max_tokens == 8  # journal

    coords = {"remote_req_id": "a", "pull_host": "h", "pull_port": 7,
              "num_tokens": 4, "remote_page_ids": [0]}
    out = EngineCoreOutput(req_id="a", new_token_ids=[42],
                           finish_reason="length",
                           kv_transfer_params=coords)
    delivered = dp._mark_finished([out])
    # The prefill finish is swallowed (its token is regenerated by the
    # decode home) and the request re-admitted to the decode pool with
    # the pull coordinates and its FULL budget.
    assert delivered == []
    assert dp._owner["a"] == 1
    (cont, ) = dp.clients[1].added
    assert cont.kv_transfer_params == coords
    assert cont.sampling_params.max_tokens == 8
    assert "a" not in dp._progress  # the swallowed token never journaled
    assert dp.disagg.handoffs == 1
    # The decode home's finish flows through normally.
    delivered = dp._mark_finished(
        [EngineCoreOutput(req_id="a", new_token_ids=[5, 6],
                          finish_reason="stop")])
    assert len(delivered) == 1
    assert dp.request_counts() == [0, 0]
    assert "a" not in dp.disagg._stage


def test_stub_coordinator_honors_pool_restriction(stub_fleet):
    """With a DP coordinator process attached, disagg placement must
    stay pool-restricted: the coordinator's fleet-wide least-loaded
    route() cannot honor pools, so the pick is made locally and the
    admission accounted to it explicitly via report()."""
    dp = stub_fleet

    class _FakeCoordinator:
        def __init__(self):
            self.reports = []

        def route(self, prefer=None):
            raise AssertionError(
                "coordinator.route() must not place disagg admissions")

        def report(self, engine, delta):
            self.reports.append((engine, delta))

    dp.coordinator = _FakeCoordinator()
    dp.add_request(_req("c"))
    assert dp._owner["c"] == 0  # prefill pool despite the coordinator
    assert (0, 1) in dp.coordinator.reports  # admission accounted
    out = EngineCoreOutput(
        req_id="c", new_token_ids=[42], finish_reason="length",
        kv_transfer_params={"remote_req_id": "c", "pull_host": "h",
                            "pull_port": 7, "num_tokens": 4,
                            "remote_page_ids": [0]})
    dp._mark_finished([out])
    assert dp._owner["c"] == 1  # decode pool, still coordinator-safe
    assert (1, 1) in dp.coordinator.reports
    # The handoff unwound the prefill-side accounting.
    assert (0, -1) in dp.coordinator.reports


def test_stub_pool_down_falls_back_to_any_alive(stub_fleet):
    dp = stub_fleet
    dp._down.add(0)  # the whole prefill pool
    dp.add_request(_req("x"))
    assert dp._owner["x"] == 1  # placed on the decode replica anyway
    assert dp.disagg.fallbacks.get("pool_down", 0) == 1


def test_stub_prefill_only_requests_are_not_staged(stub_fleet):
    dp = stub_fleet
    dp.add_request(_req("one", max_tokens=1))
    assert dp._owner["one"] == 0  # prefill pool, monolithic service
    assert "one" not in dp.disagg._stage
    (admitted, ) = dp.clients[0].added
    assert admitted is dp._requests["one"]  # no staging copy
    # Its finish passes through unintercepted.
    delivered = dp._mark_finished(
        [EngineCoreOutput(req_id="one", new_token_ids=[9],
                          finish_reason="length")])
    assert len(delivered) == 1


def test_stub_abort_clears_handoff_state(stub_fleet):
    dp = stub_fleet
    dp.add_request(_req("a"))
    dp.abort_requests(["a"])
    assert "a" not in dp.disagg._stage
    # A late prefill finish for the aborted request causes no ghost
    # re-admission (the front end already dropped the request; the
    # stray output is harmless downstream).
    dp._mark_finished(
        [EngineCoreOutput(req_id="a", new_token_ids=[1],
                          finish_reason="length")])
    assert dp.clients[1].added == []
    assert "a" not in dp._owner
