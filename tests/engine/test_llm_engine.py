"""End-to-end engine tests against a tiny on-disk HF checkpoint (model:
reference tests/basic_correctness/ comparing VllmRunner vs HfRunner)."""

import numpy as np
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config(),
                     load_tokenizer=False)


@pytest.fixture(scope="module")
def engine(checkpoint):
    path, _ = checkpoint
    return make_engine(path)


def hf_greedy(hf, prompt, n):
    with torch.no_grad():
        out = hf.generate(torch.tensor([prompt]), max_new_tokens=n,
                          do_sample=False, eos_token_id=None)
    return out[0].tolist()[len(prompt):]


_RUN_COUNTER = [0]


def run_engine(engine, prompts, sps):
    _RUN_COUNTER[0] += 1
    for i, (p, sp) in enumerate(zip(prompts, sps)):
        engine.add_request(f"t{_RUN_COUNTER[0]}-{i}", p, sp)
    done = {}
    for _ in range(500):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    return [done[k] for k in sorted(done, key=lambda s: int(s.split("-")[1]))]


def test_greedy_matches_hf(engine, checkpoint):
    _, hf = checkpoint
    prompt = [3, 17, 92, 45, 8]
    outs = run_engine(engine, [prompt],
                      [SamplingParams(temperature=0.0, max_tokens=10,
                                      ignore_eos=True)])
    assert outs[0].outputs[0].token_ids == hf_greedy(hf, prompt, 10)
    assert outs[0].outputs[0].finish_reason == "length"


def test_batch_of_ragged_prompts(engine, checkpoint):
    _, hf = checkpoint
    prompts = [[5, 9, 101], [7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7],
               [120, 44], [1, 2, 3, 4, 5, 6]]
    sps = [SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
           for _ in prompts]
    outs = run_engine(engine, prompts, sps)
    for prompt, out in zip(prompts, outs):
        assert out.outputs[0].token_ids == hf_greedy(hf, prompt, 6), \
            f"mismatch for prompt {prompt}"


def test_chunked_prefill_e2e(checkpoint):
    path, hf = checkpoint
    # Budget 16 forces a 40-token prompt through 3 prefill chunks.
    engine = make_engine(path, max_num_batched_tokens=16)
    prompt = list(np.random.default_rng(0).integers(2, 127, size=40))
    prompt = [int(x) for x in prompt]
    outs = run_engine(engine, [prompt],
                      [SamplingParams(temperature=0.0, max_tokens=5,
                                      ignore_eos=True)])
    assert outs[0].outputs[0].token_ids == hf_greedy(hf, prompt, 5)


def test_eos_stop(checkpoint):
    path, hf = checkpoint
    engine = make_engine(path)
    # Find a prompt whose greedy continuation hits token 1 (eos) — craft
    # via stop_token_ids instead: stop on whatever HF emits 3rd.
    prompt = [3, 17, 92, 45, 8]
    hf_tokens = hf_greedy(hf, prompt, 10)
    stop_tok = hf_tokens[2]
    outs = run_engine(engine, [prompt],
                      [SamplingParams(temperature=0.0, max_tokens=10,
                                      ignore_eos=True,
                                      stop_token_ids=[stop_tok])])
    assert outs[0].outputs[0].token_ids == hf_tokens[:3]
    assert outs[0].outputs[0].finish_reason == "stop"
    assert outs[0].outputs[0].stop_reason == stop_tok


def test_prefix_cache_second_request_consistent(checkpoint):
    path, hf = checkpoint
    engine = make_engine(path)
    base = [9, 8, 7, 6, 5, 4, 3, 2]
    sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)
    first = run_engine(engine, [base], [sp])
    second = run_engine(engine, [base + [60, 61]], [sp])
    assert first[0].outputs[0].token_ids == hf_greedy(hf, base, 4)
    assert second[0].outputs[0].token_ids == hf_greedy(hf, base + [60, 61],
                                                       4)
    # The second run must actually have hit the cache.
    stats = engine.get_stats()
    assert stats["hits"] >= 1


def test_seeded_sampling_reproducible(checkpoint):
    path, _ = checkpoint
    engine = make_engine(path)
    prompt = [10, 20, 30]
    sp = SamplingParams(temperature=1.0, seed=1234, max_tokens=8,
                        ignore_eos=True)
    a = run_engine(engine, [prompt], [sp])[0].outputs[0].token_ids
    b = run_engine(engine, [prompt], [sp])[0].outputs[0].token_ids
    assert a == b
    sp2 = SamplingParams(temperature=1.0, seed=99, max_tokens=8,
                         ignore_eos=True)
    c = run_engine(engine, [prompt], [sp2])[0].outputs[0].token_ids
    assert a != c  # overwhelmingly likely


def test_pallas_backend_e2e(checkpoint, monkeypatch):
    """Full engine stack through the Pallas kernel (interpret mode on CPU):
    chunked prefill + decode must match HF greedy exactly."""
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    path, hf = checkpoint
    engine = make_engine(path, max_num_batched_tokens=16)
    prompts = [[3, 17, 92, 45, 8],
               list(range(2, 25))]  # 23 tokens -> 2 prefill chunks
    sps = [SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True)
           for _ in prompts]
    outs = run_engine(engine, prompts, sps)
    for prompt, out in zip(prompts, outs):
        assert out.outputs[0].token_ids == hf_greedy(hf, prompt, 5), \
            f"pallas mismatch for prompt {prompt}"


def test_multi_step_decode_matches_hf(checkpoint):
    """num_scheduler_steps>1 fuses decode bursts on-device; outputs must be
    identical to single-step greedy (and HF)."""
    path, hf = checkpoint
    engine = make_engine(path, num_scheduler_steps=4)
    prompts = [[3, 17, 92, 45, 8], [5, 9, 101], [120, 44]]
    sps = [SamplingParams(temperature=0.0, max_tokens=9, ignore_eos=True)
           for _ in prompts]
    outs = run_engine(engine, prompts, sps)
    for prompt, out in zip(prompts, outs):
        assert out.outputs[0].token_ids == hf_greedy(hf, prompt, 9), \
            f"multi-step mismatch for prompt {prompt}"
    # Stop tokens mid-burst must truncate correctly.
    hf_tokens = hf_greedy(hf, prompts[0], 9)
    stop_tok = hf_tokens[4]
    outs = run_engine(engine, [prompts[0]],
                      [SamplingParams(temperature=0.0, max_tokens=9,
                                      ignore_eos=True,
                                      stop_token_ids=[stop_tok])])
    assert outs[0].outputs[0].token_ids == \
        hf_tokens[:hf_tokens.index(stop_tok) + 1]
    assert outs[0].outputs[0].finish_reason == "stop"


def test_multi_step_seeded_matches_single_step(checkpoint):
    path, _ = checkpoint
    sp = SamplingParams(temperature=0.9, seed=7, max_tokens=8,
                        ignore_eos=True)
    single = make_engine(path)
    multi = make_engine(path, num_scheduler_steps=4)
    prompt = [11, 22, 33, 44]
    a = run_engine(single, [prompt], [sp])[0].outputs[0].token_ids
    b = run_engine(multi, [prompt], [sp])[0].outputs[0].token_ids
    assert a == b


def test_zero_token_dispatch_does_no_device_work(engine):
    """Contract relied on by the PP batch queue's sync fallback
    (engine/core.py): a zero-token SchedulerOutput must resolve entirely
    at dispatch time (connector polls + row cleanup), never launching
    device work that could interleave with in-flight async batches."""
    from vllm_distributed_tpu.core.sched.output import SchedulerOutput
    runner = engine.engine_core.engine_core.executor.worker.model_runner
    handle = runner.dispatch_model(SchedulerOutput())
    assert "ready" in handle and "dev" not in handle
    out = runner.wait_model(handle)
    assert not out.sampled_token_ids
