"""EAGLE speculative decoding end-to-end (reference:
v1/spec_decode/eagle.py + tests/v1/e2e/test_eagle_spec_decode):
draft layers stacked onto the target's paged cache, in-step advance,
rejection-sampling verification.

The test eagle checkpoint reuses the TARGET'S OWN layers with
fc = [I | 0] (drafter input = the token embedding, the target's own
layer-0 input): the drafter's advance stream then reproduces the
target's computation exactly over its persistent draft KV, making it
the ideal EAGLE — proposals match the target distribution at every
draft position. That makes the acceptance ordering provable: EAGLE
(full persistent context) > draft_model with a truncated window >
ngram on non-repetitive text.
"""

import json
import os

import numpy as np
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

VOCAB, H, HEADS, KVH = 128, 64, 4, 2


@pytest.fixture(scope="module")
def target_hf():
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=VOCAB, hidden_size=H,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=HEADS, num_key_value_heads=KVH,
                      max_position_embeddings=128, eos_token_id=1)
    hf = HFLlama(cfg).eval()
    # Random-init logits are near-uniform over the vocab; real LMs are
    # peaked. Sharpen the head so top-8 mass at T=0.8 is ~0.9 (else
    # acceptance measures the truncated-support mass, not drafter
    # quality).
    with torch.no_grad():
        hf.lm_head.weight *= 12.0
    return hf


@pytest.fixture(scope="module")
def target_ckpt(tmp_path_factory, target_hf):
    path = tmp_path_factory.mktemp("tiny_llama_eagle_target")
    target_hf.save_pretrained(path, safe_serialization=True)
    return str(path)


@pytest.fixture(scope="module")
def eagle_ckpt(tmp_path_factory, target_hf):
    """The target's own layers + fc = [I | 0] (embedding half): the
    drafter re-runs the target's computation over its persistent draft
    KV — the ideal EAGLE, exact at every draft position."""
    from safetensors.numpy import save_file
    sd = {k: v.detach().numpy().copy()
          for k, v in target_hf.state_dict().items()
          if k.startswith("model.layers.")}
    fc = np.zeros((H, 2 * H), np.float32)
    fc[:, :H] = np.eye(H, dtype=np.float32)  # pick the embedding half
    sd["fc.weight"] = fc
    path = str(tmp_path_factory.mktemp("tiny_eagle_head"))
    save_file(sd, os.path.join(path, "model.safetensors"))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(target_hf.config.to_dict(), f)
    return path


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=256, max_model_len=128,
                max_num_batched_tokens=128, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def run(engine, prompts, sps, tag):
    for i, (p, sp) in enumerate(zip(prompts, sps)):
        engine.add_request(f"{tag}-{i}", p, sp)
    done = {}
    for _ in range(500):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    return [done[k]
            for k in sorted(done, key=lambda s: int(s.split("-")[-1]))]


PROMPTS = [
    [3, 17, 92, 45, 8, 21],
    [60, 41, 2, 99, 14],
    [25, 26, 27, 90, 33, 47, 58],
]


def rate(stats):
    return (stats["spec_num_accepted_tokens"] /
            max(1, stats["spec_num_draft_tokens"]))


def test_eagle_greedy_matches_baseline_exactly(target_ckpt, eagle_ckpt):
    sps = [SamplingParams(temperature=0.0, max_tokens=20,
                          ignore_eos=True) for _ in PROMPTS]
    expect = [o.outputs[0].token_ids
              for o in run(make_engine(target_ckpt), PROMPTS, sps, "b")]
    eagle = make_engine(target_ckpt, speculative_method="eagle",
                        speculative_model=eagle_ckpt,
                        num_speculative_tokens=1)
    got = [o.outputs[0].token_ids
           for o in run(eagle, PROMPTS, sps, "e")]
    assert got == expect
    stats = eagle.get_stats()
    assert stats["spec_num_draft_tokens"] > 0
    # First-draft proposals are exactly the target argmax here.
    assert rate(stats) > 0.9, stats


def test_eagle_beats_draft_model_beats_ngram_at_temp(target_ckpt,
                                                     eagle_ckpt):
    """VERDICT r4 #2 'done' criterion: acceptance ordering at
    temperature 0.8 on a shared non-repetitive corpus. EAGLE keeps the
    full context through its persistent draft KV; the draft model is
    window-truncated (window=4); ngram has nothing to match."""
    def sps():
        return [SamplingParams(temperature=0.8, seed=7 + i,
                               max_tokens=16, ignore_eos=True)
                for i in range(len(PROMPTS))]

    ngram = make_engine(target_ckpt, speculative_method="ngram",
                        num_speculative_tokens=1)
    run(ngram, PROMPTS, sps(), "n")
    n_rate = rate(ngram.get_stats())

    draft = make_engine(target_ckpt, speculative_method="draft_model",
                        speculative_model=target_ckpt,
                        speculative_draft_window=4,
                        num_speculative_tokens=1)
    run(draft, PROMPTS, sps(), "d")
    d_rate = rate(draft.get_stats())

    eagle = make_engine(target_ckpt, speculative_method="eagle",
                        speculative_model=eagle_ckpt,
                        num_speculative_tokens=1)
    run(eagle, PROMPTS, sps(), "g")
    e_rate = rate(eagle.get_stats())

    assert e_rate > d_rate > n_rate, (e_rate, d_rate, n_rate)
    # The identity-construction eagle proposes from exactly the target
    # distribution: expected acceptance = E[sum min(p, q)] = E[sum p]
    # = 1 up to truncated-support mass.
    assert e_rate > 0.7, e_rate


def test_eagle_seeded_reproducible(target_ckpt, eagle_ckpt):
    prompts = [[5, 9, 23, 40, 77]]
    sp = [SamplingParams(temperature=0.9, seed=42, max_tokens=12,
                         ignore_eos=True)]
    o1 = run(make_engine(target_ckpt, speculative_method="eagle",
                         speculative_model=eagle_ckpt,
                         num_speculative_tokens=2),
             prompts, sp, "r1")[0].outputs[0].token_ids
    o2 = run(make_engine(target_ckpt, speculative_method="eagle",
                         speculative_model=eagle_ckpt,
                         num_speculative_tokens=2),
             prompts, sp, "r2")[0].outputs[0].token_ids
    assert o1 == o2


def test_eagle_tp2_matches_single_device(target_ckpt, eagle_ckpt):
    """EAGLE under GSPMD TP: the draft layers' advance/propose run on
    the sharded mesh; greedy output must match tp=1 exactly."""
    sps = [SamplingParams(temperature=0.0, max_tokens=12,
                          ignore_eos=True) for _ in PROMPTS]
    single = make_engine(target_ckpt, speculative_method="eagle",
                         speculative_model=eagle_ckpt,
                         num_speculative_tokens=2)
    want = [o.outputs[0].token_ids
            for o in run(single, PROMPTS, sps, "t1")]
    tp2 = make_engine(target_ckpt, speculative_method="eagle",
                      speculative_model=eagle_ckpt,
                      num_speculative_tokens=2,
                      tensor_parallel_size=2)
    got = [o.outputs[0].token_ids
           for o in run(tp2, PROMPTS, sps, "t2")]
    assert got == want


def test_eagle_survives_preemption(target_ckpt, eagle_ckpt):
    """A page pool too small for both requests forces preemption and
    resume mid-generation; EAGLE's draft KV is rebuilt by the re-run
    prefill's in-step advance and greedy output stays exact."""
    sps = [SamplingParams(temperature=0.0, max_tokens=16,
                          ignore_eos=True) for _ in PROMPTS[:2]]
    baseline = make_engine(target_ckpt)
    want = [o.outputs[0].token_ids
            for o in run(baseline, PROMPTS[:2], sps, "pb")]

    tight = make_engine(target_ckpt, speculative_method="eagle",
                        speculative_model=eagle_ckpt,
                        num_speculative_tokens=2,
                        num_gpu_blocks_override=10)  # < 2 full requests
    got = [o.outputs[0].token_ids
           for o in run(tight, PROMPTS[:2], sps, "pe")]
    assert got == want
    sched = tight.engine_core.engine_core.scheduler
    assert sched.get_stats()["num_preemptions"] > 0


def test_eagle_sleep_wake_roundtrip(target_ckpt, eagle_ckpt):
    """Sleep level 1 offloads the param tree INCLUDING the eagle
    subtree; wake re-places it (the specs['eagle'] branch of
    model_runner.wake_up) and generation resumes exactly."""
    sps = [SamplingParams(temperature=0.0, max_tokens=10,
                          ignore_eos=True)]
    engine = make_engine(target_ckpt, speculative_method="eagle",
                         speculative_model=eagle_ckpt,
                         num_speculative_tokens=1)
    before = run(engine, [PROMPTS[0]], sps, "sw0")[0].outputs[0].token_ids
    freed = engine.sleep(level=1)
    assert freed > 0
    engine.wake_up()
    after = run(engine, [PROMPTS[0]], sps, "sw1")[0].outputs[0].token_ids
    assert after == before
