"""Wire tolerance for the trace-plane context (ISSUE 19 satellite).

The ``trace_ctx`` wire key is ADDITIVE: a rolling fleet upgrade runs
old and new peers against each other in both directions, so

* a trace-stamped request must survive a pre-trace-plane decoder
  (which constructs from known keys and drops extras), and
* a trace-plane decoder must accept the old wire, where the key simply
  never appears (``trace_ctx`` resolves to None).

With VDT_TRACE_PLANE=0 nothing mints a context, and the encoded map —
hence its msgpack bytes — must be byte-identical to the pre-plane wire.
"""

import msgpack

from vllm_distributed_tpu.engine import serial
from vllm_distributed_tpu.request import EngineCoreRequest
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.trace_plane import mint_trace_ctx


def _req(rid: str = "req-1", trace_ctx=None) -> EngineCoreRequest:
    return EngineCoreRequest(
        request_id=rid, prompt_token_ids=[1, 2, 3],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=4),
        trace_ctx=trace_ctx)


def _old_decode(d: dict) -> EngineCoreRequest:
    """The pre-trace-plane decoder: constructs from its OWN known keys
    only, never looking at trace_ctx (simulates an old peer)."""
    return EngineCoreRequest(
        request_id=d["request_id"],
        prompt_token_ids=list(d["prompt_token_ids"]),
        sampling_params=SamplingParams(**d["sampling_params"]),
        eos_token_id=d["eos_token_id"],
        arrival_time=d["arrival_time"],
        priority=d["priority"],
        tenant=d.get("tenant"),
        kv_transfer_params=d["kv_transfer_params"],
    )


def test_round_trip_carries_trace_ctx():
    ctx = mint_trace_ctx("req-1")
    wire = serial.unpack(serial.pack(serial.encode_request(
        _req(trace_ctx=ctx))))
    got = serial.decode_request(wire)
    assert got.trace_ctx == ctx
    assert got.request_id == "req-1"
    assert got.prompt_token_ids == [1, 2, 3]


def test_untraced_wire_is_byte_identical_to_pre_plane():
    # trace_ctx=None (the VDT_TRACE_PLANE=0 default) must not add the
    # key at all — the bytes on the wire are EXACTLY the old wire.
    d = serial.encode_request(_req())
    assert "trace_ctx" not in d
    pre_plane = {k: v for k, v in d.items() if k != "trace_ctx"}
    assert serial.pack(d) == msgpack.packb(pre_plane, use_bin_type=True)


def test_new_decoder_accepts_old_wire():
    # Old peer -> new decoder: the key is absent, not null.
    d = serial.encode_request(_req())
    wire = serial.unpack(serial.pack(d))
    assert "trace_ctx" not in wire
    got = serial.decode_request(wire)
    assert got.trace_ctx is None


def test_old_decoder_accepts_traced_wire():
    # New peer -> old decoder: the extra key must not break an old
    # constructor that only reads its known keys.
    d = serial.encode_request(_req(trace_ctx=mint_trace_ctx("req-1")))
    assert d["trace_ctx"] == mint_trace_ctx("req-1")
    got = _old_decode(serial.unpack(serial.pack(d)))
    assert got.request_id == "req-1"
    assert got.trace_ctx is None  # old peers simply drop the context


def test_minting_is_deterministic_and_wire_safe():
    # The disagg consumer re-mints from the SAME request id (the
    # handoff re-admits the original id), so determinism is what makes
    # both replicas land in one trace even if the ctx were dropped.
    a, b = mint_trace_ctx("req-x"), mint_trace_ctx("req-x")
    assert a == b
    assert a != mint_trace_ctx("req-y")
    assert set(a) == {"trace_id", "span_id"}
    assert len(a["trace_id"]) == 16 and len(a["span_id"]) == 8
    int(a["trace_id"], 16)  # plain hex: survives any JSON/msgpack hop
    int(a["span_id"], 16)
