"""Driver contract tests for __graft_entry__.py."""

import sys

sys.path.insert(0, "/root/repo")


def test_dryrun_multichip_8():
    import __graft_entry__ as g
    g.dryrun_multichip(8)


def test_entry_traces():
    """entry() must at least trace/lower without error (full compile of
    the 1B model is exercised by the driver on the chip)."""
    import jax

    import __graft_entry__ as g
    fn, args = g.entry()
    jax.jit(fn).lower(*args)  # shape-level validation only
