"""CI guard: every emitted vdt: metric stays documented.

Runs scripts/lint_metrics.py over the real package + README (tier-1
mechanical check) and unit-tests the linter's failure modes on
synthetic trees: an emitted-but-undocumented metric, a metric without
HELP/TYPE exposition, and an orphaned README row."""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "lint_metrics.py"


def _run(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run([sys.executable, str(SCRIPT), *args],
                          capture_output=True, text=True, timeout=60)


def _tree(tmp_path, source: str, readme: str, prometheus: str = ""):
    pkg = tmp_path / "pkg"
    (pkg / "metrics").mkdir(parents=True)
    (pkg / "emitter.py").write_text(source)
    if prometheus:
        (pkg / "metrics" / "prometheus.py").write_text(prometheus)
    readme_path = tmp_path / "README.md"
    readme_path.write_text(readme)
    return pkg, readme_path


def test_package_metrics_are_documented():
    res = _run()
    assert res.returncode == 0, (
        f"vdt: metric documentation drifted:\n{res.stderr}")


def test_undocumented_metric_is_caught(tmp_path):
    """A metric emitted with exposition but missing its README row."""
    src = ('LINES = ["# HELP vdt:bogus_total x",\n'
           '         "# TYPE vdt:bogus_total counter",\n'
           '         "vdt:bogus_total 1"]\n')
    pkg, readme = _tree(tmp_path, src, "# nothing here\n")
    res = _run("--package", str(pkg), "--readme", str(readme))
    assert res.returncode == 1
    assert "vdt:bogus_total" in res.stderr
    assert "missing from the README" in res.stderr


def test_unexposed_metric_is_caught(tmp_path):
    """A metric emitted as a bare literal with no HELP/TYPE anywhere."""
    pkg, readme = _tree(tmp_path, 'NAME = "vdt:sneaky_total"\n',
                        "| `vdt:sneaky_total` | counter | x |\n")
    res = _run("--package", str(pkg), "--readme", str(readme))
    assert res.returncode == 1
    assert "without HELP/TYPE exposition" in res.stderr


def test_orphaned_readme_row_is_caught(tmp_path):
    pkg, readme = _tree(tmp_path, "x = 1\n",
                        "| `vdt:ghost_total` | counter | gone |\n")
    res = _run("--package", str(pkg), "--readme", str(readme))
    assert res.returncode == 1
    assert "orphaned row" in res.stderr


def test_clean_synthetic_tree_passes(tmp_path):
    src = ('LINES = ["# HELP vdt:ok_total x",\n'
           '         "# TYPE vdt:ok_total counter",\n'
           '         "vdt:ok_total 1"]\n')
    pkg, readme = _tree(tmp_path, src,
                        "| `vdt:ok_total` | counter | fine |\n")
    res = _run("--package", str(pkg), "--readme", str(readme))
    assert res.returncode == 0, res.stderr


def test_missing_package_is_a_usage_error(tmp_path):
    res = _run("--package", str(tmp_path / "nope"),
               "--readme", str(tmp_path / "also-nope"))
    assert res.returncode == 2


# ---------------------------------------------------------------------------
# Labeled families: LABELED_METRICS registry <-> README label sets.
# ---------------------------------------------------------------------------
_LABELED_PROM = (
    '# HELP vdt:labeled_total x\n'
    '# TYPE vdt:labeled_total counter\n'
    'LABELED_METRICS = {\n'
    '    "vdt:labeled_total": ("conn", "dir"),\n'
    '}\n')


def test_undocumented_label_set_is_caught(tmp_path):
    """A labeled family whose README row lacks its {label} set."""
    pkg, readme = _tree(tmp_path, "x = 1\n",
                        "| `vdt:labeled_total` | counter | x |\n",
                        prometheus=_LABELED_PROM)
    res = _run("--package", str(pkg), "--readme", str(readme))
    assert res.returncode == 1
    assert "does not document them" in res.stderr
    assert "vdt:labeled_total{conn,dir}" in res.stderr


def test_spurious_readme_labels_are_caught(tmp_path):
    """A README label set the registry never declared."""
    pkg, readme = _tree(
        tmp_path, "x = 1\n",
        "| `vdt:labeled_total{conn,dir}` | counter | x |\n"
        "| `vdt:labeled_total{bogus}` | counter | dup |\n",
        prometheus=_LABELED_PROM)
    res = _run("--package", str(pkg), "--readme", str(readme))
    assert res.returncode == 1
    assert "registry declares" in res.stderr


def test_clean_labeled_tree_passes(tmp_path):
    pkg, readme = _tree(
        tmp_path, "x = 1\n",
        "| `vdt:labeled_total{conn,dir}` | counter | x |\n",
        prometheus=_LABELED_PROM)
    res = _run("--package", str(pkg), "--readme", str(readme))
    assert res.returncode == 0, res.stderr


# ---------------------------------------------------------------------------
# Dynamic (traffic-valued) labels: the {tenant} family must carry a
# bounded-cardinality note naming VDT_QOS_MAX_TRACKED_TENANTS.
# ---------------------------------------------------------------------------
_TENANT_PROM = (
    '# HELP vdt:tenant_x_total x\n'
    '# TYPE vdt:tenant_x_total counter\n'
    'LABELED_METRICS = {\n'
    '    "vdt:tenant_x_total": ("tenant", ),\n'
    '}\n')


def test_dynamic_label_without_cardinality_note_is_caught(tmp_path):
    """A {tenant} family documented with its label set but WITHOUT the
    bucketing-bound note on the row: series-explosion hazard."""
    pkg, readme = _tree(
        tmp_path, "x = 1\n",
        "| `vdt:tenant_x_total{tenant}` | counter | per tenant |\n",
        prometheus=_TENANT_PROM)
    res = _run("--package", str(pkg), "--readme", str(readme))
    assert res.returncode == 1
    assert "cardinality note" in res.stderr
    assert "VDT_QOS_MAX_TRACKED_TENANTS" in res.stderr


def test_dynamic_label_with_cardinality_note_passes(tmp_path):
    pkg, readme = _tree(
        tmp_path, "x = 1\n",
        "| `vdt:tenant_x_total{tenant}` | counter | per tenant "
        "(bounded by `VDT_QOS_MAX_TRACKED_TENANTS`) |\n",
        prometheus=_TENANT_PROM)
    res = _run("--package", str(pkg), "--readme", str(readme))
    assert res.returncode == 0, res.stderr
