"""HF greedy parity + engine behavior for the Mamba (SSM) family.

Same harness as tests/models/test_families.py (reference pattern:
tests/models/ per-arch correctness vs HfRunner), plus SSM-specific
checks: chunked prefill must thread state between chunks, and prefix
caching must be auto-disabled for stateful models.
"""

import pytest
import torch
from transformers import (FalconMambaConfig, FalconMambaForCausalLM,
                          Mamba2Config, Mamba2ForCausalLM, MambaConfig,
                          MambaForCausalLM)

from _engine_harness import PROMPTS, hf_greedy, run_engine as run
from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine


@pytest.fixture(scope="module")
def mamba_ckpt(tmp_path_factory):
    torch.manual_seed(0)
    cfg = MambaConfig(vocab_size=128, hidden_size=32, state_size=8,
                      num_hidden_layers=2, conv_kernel=4, expand=2,
                      time_step_rank=4, use_conv_bias=True,
                      use_bias=False, eos_token_id=1)
    hf = MambaForCausalLM(cfg)
    path = tmp_path_factory.mktemp("mamba-tiny")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf.eval()


def test_mamba_greedy_matches_hf(mamba_ckpt):
    path, hf = mamba_ckpt
    expect = [hf_greedy(hf, p, 6) for p in PROMPTS]
    got = run(path, PROMPTS)
    assert got == expect


def test_mamba_chunked_prefill_threads_state(mamba_ckpt):
    """A prompt longer than the token budget forces multi-chunk prefill:
    every chunk after the first must resume carried conv+ssm state."""
    path, hf = mamba_ckpt
    long_prompt = [(i * 7 + 3) % 128 for i in range(40)]
    expect = [hf_greedy(hf, long_prompt, 6)]
    got = run(path, [long_prompt], max_num_batched_tokens=16,
              max_model_len=64)
    assert got == expect


def test_mamba_preemption_recomputes(mamba_ckpt):
    """A tiny page pool forces preemption; resumed requests must restart
    their recurrence from scratch and still match HF."""
    path, hf = mamba_ckpt
    prompts = [[(i * 5 + j) % 128 for j in range(8)] for i in range(4)]
    expect = [hf_greedy(hf, p, 8) for p in prompts]
    got = run(path, prompts, max_tokens=8, num_gpu_blocks_override=20,
              max_num_seqs=4)
    assert got == expect


def test_mamba_disables_prefix_caching(mamba_ckpt):
    path, _ = mamba_ckpt
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=4,
                enable_prefix_caching=True, skip_tokenizer_init=True)
    engine = LLMEngine(EngineArgs(**args).create_engine_config())
    sched = engine.engine_core.scheduler
    assert not sched.kv_cache_manager.enable_caching


@pytest.fixture(scope="module")
def mamba2_ckpt(tmp_path_factory):
    torch.manual_seed(1)
    cfg = Mamba2Config(vocab_size=128, hidden_size=32, state_size=8,
                       num_hidden_layers=2, conv_kernel=4, expand=2,
                       num_heads=8, head_dim=8, n_groups=2,
                       chunk_size=8, use_conv_bias=True, use_bias=False,
                       tie_word_embeddings=False, eos_token_id=1)
    hf = Mamba2ForCausalLM(cfg)
    path = tmp_path_factory.mktemp("mamba2-tiny")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf.eval()


def test_mamba2_greedy_matches_hf(mamba2_ckpt):
    path, hf = mamba2_ckpt
    expect = [hf_greedy(hf, p, 6) for p in PROMPTS]
    got = run(path, PROMPTS)
    assert got == expect


def test_mamba2_chunked_prefill_threads_state(mamba2_ckpt):
    path, hf = mamba2_ckpt
    long_prompt = [(i * 11 + 5) % 128 for i in range(40)]
    expect = [hf_greedy(hf, long_prompt, 6)]
    got = run(path, [long_prompt], max_num_batched_tokens=16,
              max_model_len=64)
    assert got == expect


def test_mamba2_with_biases_matches_hf(tmp_path_factory):
    """use_bias=True exercises the in/out projection bias load path."""
    torch.manual_seed(3)
    cfg = Mamba2Config(vocab_size=128, hidden_size=32, state_size=8,
                       num_hidden_layers=2, conv_kernel=4, expand=2,
                       num_heads=8, head_dim=8, n_groups=2,
                       chunk_size=8, use_conv_bias=True, use_bias=True,
                       tie_word_embeddings=False, eos_token_id=1)
    hf = Mamba2ForCausalLM(cfg)
    # Bias init is zero in HF; randomize so the test can catch a
    # dropped/misrouted bias.
    with torch.no_grad():
        for blk in hf.backbone.layers:
            blk.mixer.in_proj.bias.normal_(std=0.1)
            blk.mixer.out_proj.bias.normal_(std=0.1)
    path = tmp_path_factory.mktemp("mamba2-bias-tiny")
    hf.save_pretrained(path, safe_serialization=True)
    hf = hf.eval()
    expect = [hf_greedy(hf, p, 6) for p in PROMPTS]
    got = run(str(path), PROMPTS)
    assert got == expect


def test_mamba2_tp2_matches_single_chip(mamba2_ckpt):
    path, hf = mamba2_ckpt
    expect = [hf_greedy(hf, p, 6) for p in PROMPTS]
    got = run(path, PROMPTS, tensor_parallel_size=2)
    assert got == expect


def test_falcon_mamba_greedy_matches_hf(tmp_path_factory):
    torch.manual_seed(2)
    cfg = FalconMambaConfig(vocab_size=128, hidden_size=32, state_size=8,
                            num_hidden_layers=2, conv_kernel=4, expand=2,
                            time_step_rank=4, eos_token_id=1)
    hf = FalconMambaForCausalLM(cfg)
    path = tmp_path_factory.mktemp("falcon-mamba-tiny")
    hf.save_pretrained(path, safe_serialization=True)
    hf = hf.eval()
    expect = [hf_greedy(hf, p, 6) for p in PROMPTS]
    got = run(str(path), PROMPTS)
    assert got == expect


def test_mamba_rejects_unwired_intersections(mamba_ckpt):
    """Spec decode (state rows cannot rewind rejected drafts) and KV
    transfer (state is not in pages) are rejected at load with clear
    errors, like the loader's other feature-intersection guards."""
    path, _ = mamba_ckpt
    base = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=4,
                skip_tokenizer_init=True)
    with pytest.raises(ValueError, match="stateful"):
        LLMEngine(EngineArgs(
            speculative_method="ngram", num_speculative_tokens=2,
            **base).create_engine_config())
    with pytest.raises(ValueError, match="stateful"):
        LLMEngine(EngineArgs(
            kv_connector="SharedStorageConnector", kv_role="kv_both",
            **base).create_engine_config())


def test_mamba_tp2_matches_single_chip(mamba_ckpt):
    """d_inner shards over the model axis; greedy tokens must match the
    single-device run exactly."""
    path, hf = mamba_ckpt
    expect = [hf_greedy(hf, p, 6) for p in PROMPTS]
    got = run(path, PROMPTS, tensor_parallel_size=2)
    assert got == expect
