"""Multi-LoRA serving: per-request adapters must match an HF model with
the adapter weights merged, including mixed batches of different
adapters (model: reference tests/lora/ correctness pattern)."""

import json
import os

import numpy as np
import pytest
import torch
from safetensors.torch import save_file
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

RANK = 4
ALPHA = 8.0
TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj", "gate_proj",
           "up_proj", "down_proj")


def _make_adapter(path, hf_cfg, seed) -> dict[str, torch.Tensor]:
    """Random PEFT-format adapter; returns per-target (A, B) tensors."""
    gen = torch.Generator().manual_seed(seed)
    tensors = {}
    L = hf_cfg.num_hidden_layers
    dims = {
        "q_proj": (hf_cfg.hidden_size, hf_cfg.hidden_size),
        "k_proj": (hf_cfg.hidden_size,
                   hf_cfg.num_key_value_heads *
                   (hf_cfg.hidden_size // hf_cfg.num_attention_heads)),
        "v_proj": (hf_cfg.hidden_size,
                   hf_cfg.num_key_value_heads *
                   (hf_cfg.hidden_size // hf_cfg.num_attention_heads)),
        "o_proj": (hf_cfg.hidden_size, hf_cfg.hidden_size),
        "gate_proj": (hf_cfg.hidden_size, hf_cfg.intermediate_size),
        "up_proj": (hf_cfg.hidden_size, hf_cfg.intermediate_size),
        "down_proj": (hf_cfg.intermediate_size, hf_cfg.hidden_size),
    }
    for layer in range(L):
        for proj, (din, dout) in dims.items():
            a = 0.1 * torch.randn(RANK, din, generator=gen)
            b = 0.1 * torch.randn(dout, RANK, generator=gen)
            base = (f"base_model.model.model.layers.{layer}"
                    f".self_attn.{proj}" if "proj" in proj and
                    proj in ("q_proj", "k_proj", "v_proj", "o_proj") else
                    f"base_model.model.model.layers.{layer}.mlp.{proj}")
            tensors[f"{base}.lora_A.weight"] = a
            tensors[f"{base}.lora_B.weight"] = b
    os.makedirs(path, exist_ok=True)
    save_file(tensors, os.path.join(path, "adapter_model.safetensors"))
    with open(os.path.join(path, "adapter_config.json"), "w") as f:
        json.dump({"r": RANK, "lora_alpha": ALPHA,
                   "target_modules": list(TARGETS)}, f)
    return tensors


def _merge_into_hf(hf: HFLlama, tensors) -> HFLlama:
    """HF baseline with W' = W + (alpha/r) * B @ A merged in."""
    import copy
    merged = copy.deepcopy(hf)
    scale = ALPHA / RANK
    with torch.no_grad():
        for layer_idx, layer in enumerate(merged.model.layers):
            mods = {
                "q_proj": layer.self_attn.q_proj,
                "k_proj": layer.self_attn.k_proj,
                "v_proj": layer.self_attn.v_proj,
                "o_proj": layer.self_attn.o_proj,
                "gate_proj": layer.mlp.gate_proj,
                "up_proj": layer.mlp.up_proj,
                "down_proj": layer.mlp.down_proj,
            }
            for proj, mod in mods.items():
                a = b = None
                for key, val in tensors.items():
                    if f"layers.{layer_idx}." in key and proj in key:
                        if "lora_A" in key:
                            a = val
                        elif "lora_B" in key:
                            b = val
                assert a is not None and b is not None, (layer_idx, proj)
                mod.weight += scale * (b @ a)
    return merged


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    root = tmp_path_factory.mktemp("tiny_llama_lora")
    hf.save_pretrained(root / "base", safe_serialization=True)
    t1 = _make_adapter(str(root / "ad1"), cfg, seed=1)
    t2 = _make_adapter(str(root / "ad2"), cfg, seed=2)
    return dict(root=root, hf=hf, cfg=cfg, t1=t1, t2=t2)


def hf_greedy(hf, prompt, n):
    with torch.no_grad():
        out = hf.generate(torch.tensor([prompt]), max_new_tokens=n,
                          do_sample=False, eos_token_id=None)
    return out[0].tolist()[len(prompt):]


PROMPTS = [[3, 17, 92, 45, 8], [5, 9, 33, 71], [11, 12, 13, 14, 15]]


def test_lora_mixed_batch_matches_merged_hf(setup):
    engine = LLMEngine(EngineArgs(
        model=str(setup["root"] / "base"), dtype="float32", block_size=4,
        num_gpu_blocks_override=128, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
        skip_tokenizer_init=True, enable_lora=True, max_loras=3,
        max_lora_rank=8).create_engine_config())

    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    loras = [
        {"name": "ad1", "path": str(setup["root"] / "ad1")},
        {"name": "ad2", "path": str(setup["root"] / "ad2")},
        None,  # plain request in the same batch
    ]
    for i, (p, lr) in enumerate(zip(PROMPTS, loras)):
        engine.add_request(f"r-{i}", p, sp, lora_request=lr)
    done = {}
    for _ in range(200):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out.outputs[0].token_ids
        if len(done) == 3:
            break
    assert len(done) == 3

    hf1 = _merge_into_hf(setup["hf"], setup["t1"])
    hf2 = _merge_into_hf(setup["hf"], setup["t2"])
    assert done["r-0"] == hf_greedy(hf1, PROMPTS[0], 6)
    assert done["r-1"] == hf_greedy(hf2, PROMPTS[1], 6)
    assert done["r-2"] == hf_greedy(setup["hf"], PROMPTS[2], 6)
    # Different adapters really produced different generations (the
    # random adapters perturb the tiny model heavily).
    assert len({tuple(v) for v in done.values()}) >= 2


def test_lora_slot_reuse_and_eviction(setup):
    engine = LLMEngine(EngineArgs(
        model=str(setup["root"] / "base"), dtype="float32", block_size=4,
        num_gpu_blocks_override=128, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
        skip_tokenizer_init=True, enable_lora=True, max_loras=1,
        max_lora_rank=8).create_engine_config())
    runner = engine.engine_core.engine_core.executor.worker.model_runner

    sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)

    def run_one(tag, lr):
        engine.add_request(tag, PROMPTS[0], sp, lora_request=lr)
        for _ in range(100):
            for out in engine.step():
                if out.finished:
                    return out.outputs[0].token_ids
        raise AssertionError("did not finish")

    got1 = run_one("a", {"name": "ad1", "path": str(setup["root"] / "ad1")})
    # Second adapter evicts the first from the single slot.
    run_one("b", {"name": "ad2", "path": str(setup["root"] / "ad2")})
    assert "ad2" in runner.lora_manager.name_to_slot
    assert "ad1" not in runner.lora_manager.name_to_slot
    # Reloading the first adapter reproduces its generation exactly.
    got1_again = run_one(
        "c", {"name": "ad1", "path": str(setup["root"] / "ad1")})
    assert got1_again == got1


def test_lora_under_pipeline_parallelism(setup):
    """PP slices the stacked LoRA buffers per stage like any layer
    weight; adapter output must still match merged HF."""
    engine = LLMEngine(EngineArgs(
        model=str(setup["root"] / "base"), dtype="float32", block_size=4,
        num_gpu_blocks_override=128, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
        skip_tokenizer_init=True, enable_lora=True, max_loras=2,
        max_lora_rank=8,
        pipeline_parallel_size=2).create_engine_config())
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    engine.add_request("pp-0", PROMPTS[0], sp,
                       lora_request={"name": "ad1",
                                     "path": str(setup["root"] / "ad1")})
    done = {}
    for _ in range(200):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out.outputs[0].token_ids
        if done:
            break
    hf1 = _merge_into_hf(setup["hf"], setup["t1"])
    assert done["pp-0"] == hf_greedy(hf1, PROMPTS[0], 6)
