"""GPT-lineage family parity (reference pattern: per-family HF-vs-engine
greedy comparisons, tests/models/ of the reference repo). GPT-2 / GPT-J /
GPTBigCode / OPT compare against their transformers implementations;
MiniCPM and EXAONE (trust_remote_code upstream, no HF class baked in)
are proven by renamed-checkpoint equivalence against Llama."""

import json
import os

import numpy as np
import pytest
import torch
import transformers
from safetensors.numpy import save_file

from tests.models._engine_harness import PROMPTS, hf_greedy, run_engine


def _save(tmp_path_factory, name, hf):
    path = str(tmp_path_factory.mktemp(name))
    hf.save_pretrained(path, safe_serialization=True)
    return path, hf


def _check(path, hf, n=6, **overrides):
    got = run_engine(path, PROMPTS, max_tokens=n, **overrides)
    for p, toks in zip(PROMPTS, got):
        assert toks == hf_greedy(hf, p, n), f"prompt {p}"


def test_gpt2_matches_hf(tmp_path_factory):
    cfg = transformers.GPT2Config(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        n_inner=None, activation_function="gelu_new", eos_token_id=1)
    torch.manual_seed(0)
    path, hf = _save(tmp_path_factory, "tiny_gpt2",
                     transformers.GPT2LMHeadModel(cfg).eval())
    _check(path, hf)


def test_gptj_matches_hf(tmp_path_factory):
    cfg = transformers.GPTJConfig(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, rotary_dim=8,
        n_positions=64, n_inner=None, activation_function="gelu_new",
        eos_token_id=1, tie_word_embeddings=False)
    torch.manual_seed(1)
    path, hf = _save(tmp_path_factory, "tiny_gptj",
                     transformers.GPTJForCausalLM(cfg).eval())
    _check(path, hf)


def test_gpt_bigcode_mqa_matches_hf(tmp_path_factory):
    cfg = transformers.GPTBigCodeConfig(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        n_inner=128, activation_function="gelu_pytorch_tanh",
        multi_query=True, eos_token_id=1)
    torch.manual_seed(2)
    path, hf = _save(tmp_path_factory, "tiny_bigcode",
                     transformers.GPTBigCodeForCausalLM(cfg).eval())
    _check(path, hf)


def test_opt_matches_hf(tmp_path_factory):
    cfg = transformers.OPTConfig(
        vocab_size=128, hidden_size=64, ffn_dim=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, do_layer_norm_before=True,
        activation_function="relu", eos_token_id=1)
    torch.manual_seed(3)
    path, hf = _save(tmp_path_factory, "tiny_opt",
                     transformers.OPTForCausalLM(cfg).eval())
    _check(path, hf)


def test_learned_positions_reject_overlong_max_model_len(
        tmp_path_factory):
    """An explicit --max-model-len past the wpe table must refuse at
    load (a clip would silently reuse the last position row)."""
    cfg = transformers.GPT2Config(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=32,
        activation_function="gelu_new", eos_token_id=1)
    torch.manual_seed(9)
    path, _ = _save(tmp_path_factory, "tiny_gpt2_cap",
                    transformers.GPT2LMHeadModel(cfg).eval())
    with pytest.raises(ValueError, match="learned-position capacity"):
        run_engine(path, [PROMPTS[0]], max_tokens=2, max_model_len=64)


def test_gpt2_matches_hf_under_tp2(tmp_path_factory):
    """Learned positions + packed-QKV split survive GSPMD TP."""
    cfg = transformers.GPT2Config(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        activation_function="gelu_new", eos_token_id=1)
    torch.manual_seed(4)
    path, hf = _save(tmp_path_factory, "tiny_gpt2_tp",
                     transformers.GPT2LMHeadModel(cfg).eval())
    _check(path, hf, tensor_parallel_size=2)


# ---------------------------------------------------------------------------
# Renamed-checkpoint equivalence for families without a baked HF class.
# ---------------------------------------------------------------------------
CFG = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=2, max_position_embeddings=64,
           eos_token_id=1)


@pytest.fixture(scope="module")
def llama_base(tmp_path_factory):
    torch.manual_seed(5)
    hf = transformers.LlamaForCausalLM(transformers.LlamaConfig(**CFG))
    path = str(tmp_path_factory.mktemp("tiny_llama_gptfam"))
    hf.save_pretrained(path, safe_serialization=True)
    return path


def _state(path):
    import glob

    from safetensors import safe_open
    out = {}
    for f in glob.glob(os.path.join(path, "*.safetensors")):
        with safe_open(f, framework="np") as r:
            for k in r.keys():
                out[k] = r.get_tensor(k)
    return out


def _save_variant(tmp_path_factory, name, arch, tensors, extra_cfg=None):
    path = str(tmp_path_factory.mktemp(name))
    save_file(tensors, os.path.join(path, "model.safetensors"))
    cfg = dict(CFG, architectures=[arch], model_type="llama",
               **(extra_cfg or {}))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(cfg, f)
    return path


def _run(path):
    return run_engine(path, PROMPTS, max_tokens=6,
                      num_gpu_blocks_override=64)


def test_minicpm_neutral_scales_equivalence(llama_base,
                                            tmp_path_factory):
    """MiniCPM with neutral MUP scales == the Llama it was renamed
    from; non-neutral scales change outputs (knob is live)."""
    sd = _state(llama_base)
    neutral = _save_variant(
        tmp_path_factory, "tiny_minicpm", "MiniCPMForCausalLM", sd,
        {"scale_emb": 1.0,
         "scale_depth": float(np.sqrt(CFG["num_hidden_layers"])),
         "dim_model_base": CFG["hidden_size"]})
    assert _run(neutral) == _run(llama_base)
    scaled = _save_variant(
        tmp_path_factory, "tiny_minicpm_sc", "MiniCPMForCausalLM", sd,
        {"scale_emb": 4.0, "scale_depth": 1.4,
         "dim_model_base": CFG["hidden_size"] // 2})
    assert _run(scaled) != _run(llama_base)


def test_exaone_renamed_equivalence(llama_base, tmp_path_factory):
    sd = _state(llama_base)
    out = {"transformer.wte.weight": sd["model.embed_tokens.weight"],
           "transformer.ln_f.weight": sd["model.norm.weight"],
           "lm_head.weight": sd["lm_head.weight"]}
    for i in range(CFG["num_hidden_layers"]):
        src = f"model.layers.{i}."
        dst = f"transformer.h.{i}."
        out[dst + "ln_1.weight"] = sd[src + "input_layernorm.weight"]
        out[dst + "ln_2.weight"] = \
            sd[src + "post_attention_layernorm.weight"]
        for p in ("q", "k", "v"):
            out[dst + f"attn.attention.{p}_proj.weight"] = \
                sd[src + f"self_attn.{p}_proj.weight"]
        out[dst + "attn.attention.out_proj.weight"] = \
            sd[src + "self_attn.o_proj.weight"]
        out[dst + "mlp.c_fc_0.weight"] = sd[src + "mlp.gate_proj.weight"]
        out[dst + "mlp.c_fc_1.weight"] = sd[src + "mlp.up_proj.weight"]
        out[dst + "mlp.c_proj.weight"] = sd[src + "mlp.down_proj.weight"]
    path = _save_variant(tmp_path_factory, "tiny_exaone",
                         "ExaoneForCausalLM", out,
                         {"activation_function": "silu"})
    assert _run(path) == _run(llama_base)

@pytest.mark.parametrize("arch,cfg_name,kw", [
    ("helium", "HeliumConfig", dict()),
    ("ernie45", "Ernie4_5Config", dict(use_bias=True)),
    ("seed_oss", "SeedOssConfig", dict(attention_bias=True,
                                       attention_out_bias=True,
                                       mlp_bias=True)),
    ("arcee", "ArceeConfig", dict(attention_bias=True,
                                  mlp_bias=True)),
])
def test_llama_math_forks_match_hf(tmp_path_factory, arch, cfg_name, kw):
    """Helium / ERNIE 4.5 / Seed-OSS / Arcee: Llama-shaped forks with
    bias or MLP twists (reference: their models/*.py entries)."""
    import transformers

    cfg_cls = getattr(transformers, cfg_name)
    model_cls = getattr(transformers,
                        cfg_name.replace("Config", "ForCausalLM"))
    cfg = cfg_cls(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=64,
                  head_dim=16, eos_token_id=1, pad_token_id=0, **kw)
    torch.manual_seed(41)
    hf = model_cls(cfg).eval()
    # HF zero-inits Linear biases: randomize so dropped-bias bugs
    # actually change outputs (a zero bias is vacuously "loaded").
    with torch.no_grad():
        for name, par in hf.named_parameters():
            if name.endswith(".bias"):
                par.normal_(0.0, 0.2)
    path = str(tmp_path_factory.mktemp(f"tiny_{arch}"))
    hf.save_pretrained(path, safe_serialization=True)
    got = run_engine(path, PROMPTS, max_tokens=6)
    for p, toks in zip(PROMPTS, got):
        assert toks == hf_greedy(hf, p, 6), f"prompt {p}"


@pytest.mark.parametrize("family", ["biogpt", "xglm"])
def test_opt_shaped_round5_families_match_hf(family, tmp_path_factory):
    """BioGPT (learned positions + gelu + scaled embeddings) and XGLM
    (fixed sinusoidal positions materialized at load)."""
    from transformers import (BioGptConfig, BioGptForCausalLM,
                              XGLMConfig, XGLMForCausalLM)
    if family == "biogpt":
        cfg = BioGptConfig(vocab_size=128, hidden_size=64,
                           intermediate_size=128, num_hidden_layers=2,
                           num_attention_heads=4,
                           max_position_embeddings=64, pad_token_id=0,
                           eos_token_id=1)
        hf_cls = BioGptForCausalLM
    else:
        cfg = XGLMConfig(vocab_size=128, d_model=64, ffn_dim=128,
                         num_layers=2, attention_heads=4,
                         max_position_embeddings=64, pad_token_id=1,
                         eos_token_id=1)
        hf_cls = XGLMForCausalLM
    torch.manual_seed(0)
    hf = hf_cls(cfg).eval()
    path = str(tmp_path_factory.mktemp(f"tiny_{family}"))
    hf.save_pretrained(path, safe_serialization=True)
    prompts = [[3, 17, 92, 45, 8], [5, 9, 33, 71]]
    got = run_engine(path, prompts)
    with torch.no_grad():
        want = [hf.generate(torch.tensor([p]), max_new_tokens=6,
                            do_sample=False, eos_token_id=None
                            )[0].tolist()[len(p):] for p in prompts]
    assert got == want, family
