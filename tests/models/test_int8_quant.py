"""Int8 weight quantization (w8a16 quantize-on-load): parity within
tolerance vs full precision, and weight bytes actually halved (model:
reference tests/tpu/test_quantization_accuracy.py +
quantization/tpu_int8.py semantics)."""

import jax
import numpy as np
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_q8")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def first_logprobs(engine, prompt, k=5):
    engine.add_request("q", prompt,
                       SamplingParams(temperature=0.0, max_tokens=1,
                                      ignore_eos=True, logprobs=k))
    for _ in range(50):
        for out in engine.step():
            if out.finished:
                return out.outputs[0].logprobs[0]
    raise AssertionError("did not finish")


def param_bytes(engine):
    runner = engine.engine_core.engine_core.executor.worker.model_runner
    return sum(x.nbytes for x in jax.tree_util.tree_leaves(runner.params))


PROMPT = [3, 17, 92, 45, 8, 21, 33]


@pytest.mark.parametrize("scheme,tol,dtype_name", [
    ("int8", 0.15, "int8"),
    ("fp8", 0.25, "float8_e4m3fn"),
    # int4: a native 4-bit weight datapath (XLA packs int4 two-per-byte
    # in TPU HBM; the CPU test backend stores bytes, so only the dtype
    # is asserted here, not the footprint).
    ("int4", 0.9, "int4"),
    # w8a8: int8 weights AND dynamic per-token int8 activations — the
    # dots run int8 x int8 with int32 accumulation (MXU-native).
    ("w8a8", 0.3, "int8"),
])
def test_quant_logit_parity_and_memory(checkpoint, scheme, tol,
                                       dtype_name):
    fp = make_engine(checkpoint)
    q8 = make_engine(checkpoint, quantization=scheme)

    lp_fp = first_logprobs(fp, PROMPT)
    lp_q8 = first_logprobs(q8, PROMPT)
    # Same top-1 and close logprobs for the shared top tokens.
    assert max(lp_fp, key=lp_fp.get) == max(lp_q8, key=lp_q8.get)
    common = set(lp_fp) & set(lp_q8)
    assert len(common) >= 3
    for tok in common:
        assert abs(lp_fp[tok] - lp_q8[tok]) < tol, (
            tok, lp_fp[tok], lp_q8[tok])

    # Weight footprint: ~4x smaller vs float32 engine weights (8-bit
    # payloads, scales negligible; embed/lm_head stay fp). int4 packs
    # only on real TPU HBM, so the byte assertion covers 8-bit schemes.
    if scheme != "int4":
        b_fp, b_q8 = param_bytes(fp), param_bytes(q8)
        assert b_q8 < 0.55 * b_fp, (b_q8, b_fp)

    # The runner's weight tree really holds quantized leaves.
    runner = q8.engine_core.engine_core.executor.worker.model_runner
    dtypes = {str(x.dtype)
              for x in jax.tree_util.tree_leaves(runner.params)}
    assert dtype_name in dtypes


@pytest.mark.parametrize("scheme", ["int8", "w8a8"])
def test_quant_greedy_decode_stable_under_tp(checkpoint, scheme):
    """Quantized + TP=2 must equal the single-device engine. int8:
    scale sharding must match the weight sharding. w8a8: the per-token
    activation absmax must cover the FULL feature row (GSPMD reduces
    across shards for the row-parallel dots)."""
    base = make_engine(checkpoint, quantization=scheme)
    tp2 = make_engine(checkpoint, quantization=scheme,
                      tensor_parallel_size=2)
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)

    def run(engine):
        engine.add_request("r", PROMPT, sp)
        for _ in range(100):
            for out in engine.step():
                if out.finished:
                    return out.outputs[0].token_ids
        raise AssertionError("did not finish")

    got_base = run(base)
    got_tp2 = run(tp2)
    assert got_base == got_tp2


def test_int8_quant_error_bounded():
    """Unit check of the quantizer itself: per-channel int8 round-trip
    error stays within one scale step."""
    from vllm_distributed_tpu.models.llama import (LlamaArchConfig,
                                                   LlamaForCausalLM)
    cfg = LlamaArchConfig(vocab_size=32, hidden_size=16,
                          intermediate_size=32, num_layers=1,
                          num_q_heads=2, num_kv_heads=2, head_dim=8,
                          quantization="int8", dtype=np.float32)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    w = rng.standard_normal((1, 16, 16)).astype(np.float32)
    params = {"layers": {"wq": w.copy()}}
    out = model.quantize_params(params)
    q = np.asarray(out["layers"]["wq"])
    s = np.asarray(out["layers"]["wq_scale"])
    assert q.dtype == np.int8
    recon = q.astype(np.float32) * s
    err = np.abs(recon - w)
    assert float(err.max()) <= float(s.max()) * 0.5 + 1e-6


def test_fp8_kv_cache_parity_and_footprint(checkpoint):
    """--kv-cache-dtype fp8: halved KV bytes, outputs within quant
    tolerance (reference: the kv_cache_dtype flag + fp8 cache
    kernels; scale 1.0)."""
    fp = make_engine(checkpoint)
    q = make_engine(checkpoint, kv_cache_dtype="fp8")
    lp_fp = first_logprobs(fp, PROMPT)
    lp_q = first_logprobs(q, PROMPT)
    assert max(lp_fp, key=lp_fp.get) == max(lp_q, key=lp_q.get)
    common = set(lp_fp) & set(lp_q)
    for tok in common:
        assert abs(lp_fp[tok] - lp_q[tok]) < 0.15

    def cache_bytes(engine):
        runner = engine.engine_core.engine_core.executor.worker \
            .model_runner
        return sum(x.nbytes
                   for x in jax.tree_util.tree_leaves(runner.kv_caches))

    assert cache_bytes(q) <= 0.3 * cache_bytes(fp)  # fp8 vs float32

    # Greedy decode stays stable over several tokens.
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    q.add_request("d", PROMPT, sp)
    for _ in range(60):
        done = [o for o in q.step() if o.finished]
        if done:
            assert len(done[0].outputs[0].token_ids) == 6
            break
    else:
        raise AssertionError("fp8 decode did not finish")


def test_fp8_kv_cache_under_tp2(checkpoint):
    """fp8 pages + GSPMD TP: the head-sharded cache keeps parity with
    the single-device fp8 engine."""
    base = make_engine(checkpoint, kv_cache_dtype="fp8")
    tp2 = make_engine(checkpoint, kv_cache_dtype="fp8",
                      tensor_parallel_size=2)
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)

    def run(engine):
        engine.add_request("r", PROMPT, sp)
        for _ in range(100):
            for out in engine.step():
                if out.finished:
                    return out.outputs[0].token_ids
        raise AssertionError("did not finish")

    assert run(base) == run(tp2)


def test_quant_decode_via_fused_pallas_kernel(checkpoint, monkeypatch):
    """With the pallas backend on one chip, decode-sized weight-only
    dots route through the fused dequant-GEMM kernel
    (ops/pallas_quant_matmul.py): greedy output must match the XLA
    dequant-in-dot path exactly."""
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "xla")
    base = make_engine(checkpoint, quantization="int4")
    sp = [SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)]

    def run_one(engine):
        engine.add_request("k", PROMPT, sp[0])
        for _ in range(100):
            for out in engine.step():
                if out.finished:
                    return out.outputs[0].token_ids
        raise AssertionError("did not finish")

    want = run_one(base)
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    fused = make_engine(checkpoint, quantization="int4")
    got = run_one(fused)
    assert got == want
