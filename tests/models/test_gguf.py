"""GGUF checkpoint loading (reference: model_loader/gguf_loader.py):
a llama GGUF file — config from metadata, llama.cpp tensor names, the
q/k rope permute, Q8_0 block quantization — loads and generates."""

import os

import numpy as np
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.models.gguf import read_gguf, write_gguf
from vllm_distributed_tpu.sampling_params import SamplingParams

CFG = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=2, max_position_embeddings=64,
           eos_token_id=1)


@pytest.fixture(scope="module")
def hf_and_paths(tmp_path_factory):
    torch.manual_seed(0)
    hf = HFLlama(LlamaConfig(**CFG)).eval()
    st_path = str(tmp_path_factory.mktemp("tiny_llama_st"))
    hf.save_pretrained(st_path, safe_serialization=True)
    gdir = tmp_path_factory.mktemp("tiny_llama_gguf")
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    f32 = os.path.join(str(gdir), "model-f32.gguf")
    q8 = os.path.join(str(gdir), "model-q8_0.gguf")
    write_gguf(f32, hf.config, sd, quant="f32")
    write_gguf(q8, hf.config, sd, quant="q8_0")
    return st_path, f32, q8


def _run(path, **overrides):
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    engine = LLMEngine(EngineArgs(**args).create_engine_config())
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    engine.add_request("g", [3, 17, 92, 45, 8], sp)
    for _ in range(100):
        for out in engine.step():
            if out.finished:
                return out.outputs[0].token_ids
    raise AssertionError("did not finish")


def test_reader_roundtrip(hf_and_paths):
    _st, f32, _q8 = hf_and_paths
    meta, tensors = read_gguf(f32)
    assert meta["general.architecture"] == "llama"
    assert int(meta["llama.block_count"]) == CFG["num_hidden_layers"]
    assert tensors["token_embd.weight"].shape == (128, 64)
    # v_proj is unpermuted: bytes must round-trip exactly.
    hf = HFLlama(LlamaConfig(**CFG))
    torch.manual_seed(0)
    hf = HFLlama(LlamaConfig(**CFG)).eval()
    want = hf.state_dict()[
        "model.layers.0.self_attn.v_proj.weight"].numpy()
    np.testing.assert_array_equal(tensors["blk.0.attn_v.weight"], want)


def test_gguf_f32_matches_safetensors(hf_and_paths):
    st, f32, _q8 = hf_and_paths
    assert _run(f32) == _run(st)


def test_gguf_q8_0_generates_consistently(hf_and_paths):
    st, _f32, q8 = hf_and_paths
    got = _run(q8)
    want = _run(st)
    # Q8_0 is near-lossless: the greedy prefix survives quantization.
    assert got[:3] == want[:3]
    assert len(got) == 6


def test_gguf_composes_with_requantization(hf_and_paths):
    """GGUF load -> --quantization int8 (requantize after the host
    dequant): the same composition the safetensors path supports."""
    st, f32, _q8 = hf_and_paths
    got = _run(f32, quantization="int8")
    want = _run(st, quantization="int8")
    assert got == want
