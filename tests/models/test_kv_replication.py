"""KV-head replication for tp > num_kv_heads (reference:
QKVParallelLinear kv-head replication in
vllm/model_executor/layers/linear.py; the Llama-3-70B shape class —
8 kv heads, TP=16 — needs this on any pod slice wider than the head
count)."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.models.llama import (LlamaArchConfig,
                                               LlamaForCausalLM)
from vllm_distributed_tpu.sampling_params import SamplingParams

from vllm_distributed_tpu.models.common import AttentionBatch

PAGE_SIZE = 4
NUM_PAGES = 32


def tiny_hf_config(**overrides):
    cfg = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
               num_hidden_layers=3, num_attention_heads=4,
               num_key_value_heads=2, max_position_embeddings=64,
               rope_theta=10000.0, tie_word_embeddings=False)
    cfg.update(overrides)
    return LlamaConfig(**cfg)


def run_ours(model, params, token_ids):
    T = len(token_ids)
    positions = list(range(T))
    kv_caches = model.make_kv_caches(NUM_PAGES, PAGE_SIZE)
    bt = np.zeros((1, 8), np.int32)
    bt[0, :4] = (1, 2, 3, 4)
    slot = [bt[0, p // PAGE_SIZE] * PAGE_SIZE + p % PAGE_SIZE
            for p in positions]
    batch = AttentionBatch(
        req_idx=jnp.zeros((T, ), jnp.int32),
        positions=jnp.asarray(positions, jnp.int32),
        slot_mapping=jnp.asarray(slot, jnp.int32),
        block_tables=jnp.asarray(bt),
        seq_lens=jnp.asarray([T], jnp.int32),
    )
    hidden, kv_caches = model.forward(params, kv_caches,
                                      jnp.asarray(token_ids, jnp.int32),
                                      batch)
    logits = model.compute_logits(params, hidden)
    return np.asarray(logits), kv_caches


def test_replicated_kv_logits_match_unreplicated():
    """Replicated heads (repeat-per-head) must be a numerical no-op."""
    torch.manual_seed(4)
    hf = HFLlama(tiny_hf_config()).eval()
    tensors = {k: v.detach().numpy() for k, v in hf.state_dict().items()}

    base_arch = LlamaArchConfig.from_hf_config(hf.config, dtype=jnp.float32)
    base = LlamaForCausalLM(base_arch)
    base_params = base.params_from_hf_state_dict(tensors)

    rep_arch = LlamaArchConfig.from_hf_config(hf.config, dtype=jnp.float32)
    rep_arch.num_kv_head_replicas = 2  # 2 kv heads -> 4 cache heads
    rep = LlamaForCausalLM(rep_arch)
    rep_params = rep.params_from_hf_state_dict(tensors)
    assert rep_params["layers"]["wk"].shape[-1] == \
        2 * base_params["layers"]["wk"].shape[-1]

    prompt = [3, 17, 92, 45, 8, 77]
    want, _ = run_ours(base, base_params, prompt)
    got, _ = run_ours(rep, rep_params, prompt)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=8,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_gqa")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


def test_tp8_with_2_kv_heads_matches_hf(checkpoint):
    """TP wider than the kv-head count through the full engine: 8-way
    model axis over 2 checkpoint kv heads (x4 replication)."""
    path, hf = checkpoint
    engine = LLMEngine(EngineArgs(
        model=path, dtype="float32", block_size=4,
        num_gpu_blocks_override=64, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=4,
        tensor_parallel_size=8,
        skip_tokenizer_init=True).create_engine_config())
    prompts = [[3, 17, 92, 45, 8], [5, 9, 33, 71]]
    for i, p in enumerate(prompts):
        engine.add_request(f"r{i}", p, SamplingParams(
            temperature=0.0, max_tokens=6, ignore_eos=True))
    done = {}
    for _ in range(100):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    got = [done[f"r{i}"].outputs[0].token_ids for i in range(len(prompts))]
    want = []
    for p in prompts:
        with torch.no_grad():
            out = hf.generate(torch.tensor([p]), max_new_tokens=6,
                              do_sample=False, eos_token_id=None)
        want.append(out[0].tolist()[len(p):])
    assert got == want
