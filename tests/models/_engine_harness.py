"""Shared tiny-model engine harness for per-family parity tests
(reference pattern: the HfRunner/VllmRunner pair of tests/conftest.py
in the reference repo — build a tiny HF checkpoint, drive the full
engine, compare greedy tokens)."""

import torch

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

PROMPTS = [
    [3, 17, 92, 45, 8, 21, 60, 5],
    [5, 9, 33, 71],
    [2, 7],
]


def hf_greedy(hf, prompt, n):
    with torch.no_grad():
        out = hf.generate(torch.tensor([prompt]), max_new_tokens=n,
                          do_sample=False, eos_token_id=None)
    return out[0].tolist()[len(prompt):]


def run_engine(path, prompts, max_tokens=6, **overrides):
    """Greedy-decode ``prompts`` through the full engine; returns the
    generated token id lists in prompt order."""
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    engine = LLMEngine(EngineArgs(**args).create_engine_config())
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                        ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"r-{i}", p, sp)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    return [done[f"r-{i}"].outputs[0].token_ids
            for i in range(len(prompts))]
