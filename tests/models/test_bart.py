"""BART encoder-decoder text generation through the paged engine
(reference: models/bart.py, the reference's encoder-decoder text
family): HF greedy parity from source token ids, variable-length cross
masking across a batch."""

import numpy as np
import pytest
import torch
import transformers

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    cfg = transformers.BartConfig(
        vocab_size=96, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_position_embeddings=64, scale_embedding=True,
        activation_function="gelu", decoder_start_token_id=2,
        eos_token_id=1, pad_token_id=0, bos_token_id=3,
        forced_eos_token_id=None)
    torch.manual_seed(0)
    hf = transformers.BartForConditionalGeneration(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_bart"))
    hf.save_pretrained(path, safe_serialization=True)
    return path, hf


def hf_greedy(hf, src, prompt, n):
    ids = list(prompt)
    src_t = torch.tensor([src])
    with torch.no_grad():
        for _ in range(n):
            out = hf(input_ids=src_t,
                     decoder_input_ids=torch.tensor([ids]))
            ids.append(int(out.logits[0, -1].argmax()))
    return ids[len(prompt):]


def _run(path, reqs, n=6):
    engine = LLMEngine(EngineArgs(
        model=path, dtype="float32", block_size=4,
        num_gpu_blocks_override=64, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
        skip_tokenizer_init=True).create_engine_config())
    sp = SamplingParams(temperature=0.0, max_tokens=n, ignore_eos=True)
    for i, (prompt, src) in enumerate(reqs):
        engine.add_request(f"b-{i}", prompt, sp,
                           multi_modal_data={"encoder_input_ids": src})
    done = {}
    for _ in range(200):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out.outputs[0].token_ids
        if not engine.has_unfinished_requests():
            break
    return [done[f"b-{i}"] for i in range(len(reqs))]


def test_bart_greedy_matches_hf(ckpt):
    path, hf = ckpt
    src = [3, 17, 45, 8, 21, 1]
    prompt = [2, 3]
    got = _run(path, [(prompt, src)], n=6)[0]
    assert got == hf_greedy(hf, src, prompt, 6)


def test_bart_variable_length_sources_batch(ckpt):
    """Two requests with DIFFERENT source lengths in one batch: the
    xlen mask must keep each decoder attending only its own valid
    source span."""
    path, hf = ckpt
    src_a = [3, 17, 45, 8, 21, 60, 33, 1]
    src_b = [3, 9, 1]
    got = _run(path, [([2, 3], src_a), ([2, 3], src_b)], n=5)
    assert got[0] == hf_greedy(hf, src_a, [2, 3], 5)
    assert got[1] == hf_greedy(hf, src_b, [2, 3], 5)
