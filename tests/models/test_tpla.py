"""TPLA: tensor-parallel latent attention (ops/mla.py, PAPERS.md
"TPLA") — the MLA latent cache shards over the TP axis so per-rank
latent-pool bytes drop ~TP-fold. These tests pin the acceptance
criteria: greedy outputs token-identical to the replicated layout on
2- and 4-device CPU meshes (XLA scan and interpret-mode Pallas
backends), per-rank capacity scaling ~TP x at a fixed HBM budget
through the worker's real accounting path, wholesale VDT_TPLA=0
revert, and the KV-transfer latent wire format round-tripping between
meshes of DIFFERENT TP degree bit-exactly (shared_storage raw files)."""

import os

import pytest
from transformers import DeepseekV2Config

from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                         KVTransferConfig, LoadConfig,
                                         ModelConfig, ParallelConfig,
                                         SchedulerConfig)
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

PROMPTS = [
    [3, 17, 92, 45, 8],
    [5, 9, 33, 71],
    [11, 12, 13, 14, 15, 16, 17, 18, 19],
]


def _hf_config():
    # kv_lora_rank divisible by 4 so TP {2, 4} both shard evenly; rope
    # dim kept small so the replicated "pe" sidecar stays a minor cost
    # (the capacity ratio approaches TP x as Lkv/R grows, like the real
    # DeepSeek 512/64 geometry).
    return DeepseekV2Config(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        moe_intermediate_size=48, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=4, q_lora_rank=None,
        kv_lora_rank=64, qk_nope_head_dim=16, qk_rope_head_dim=8,
        v_head_dim=16, n_routed_experts=4, num_experts_per_tok=2,
        n_shared_experts=1, first_k_dense_replace=1,
        routed_scaling_factor=1.0, topk_method="greedy", n_group=1,
        topk_group=1, norm_topk_prob=False, max_position_embeddings=64,
        eos_token_id=1, head_dim=8,
        architectures=["DeepseekV2ForCausalLM"])


def make_config(tp=1, tpla=True, storage=None, role=None,
                num_blocks=64) -> EngineConfig:
    os.environ["VDT_TPLA"] = "1" if tpla else "0"
    mc = ModelConfig(model="dummy-dsv2-tpla", dtype="float32",
                     max_model_len=64, skip_tokenizer_init=True)
    mc.hf_config = _hf_config()
    cfg = EngineConfig(
        model_config=mc,
        cache_config=CacheConfig(block_size=4, num_gpu_blocks=num_blocks),
        scheduler_config=SchedulerConfig(max_num_batched_tokens=64,
                                         max_num_seqs=8,
                                         max_model_len=64),
        parallel_config=ParallelConfig(tensor_parallel_size=tp),
        load_config=LoadConfig(load_format="dummy"),
    )
    if storage is not None:
        cfg.kv_transfer_config = KVTransferConfig(
            kv_connector="SharedStorageConnector", kv_role=role,
            kv_connector_extra_config={"shared_storage_path": storage})
    return cfg


def make_engine(**kw) -> LLMEngine:
    return LLMEngine(make_config(**kw), load_tokenizer=False)


def run(engine, tag, max_tokens=8, shutdown=True):
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                        ignore_eos=True)
    for i, p in enumerate(PROMPTS):
        engine.add_request(f"{tag}-{i}", list(p), sp)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = list(out.outputs[0].token_ids)
        if not engine.has_unfinished_requests():
            break
    assert len(done) == len(PROMPTS)
    out = [done[f"{tag}-{i}"] for i in range(len(PROMPTS))]
    if shutdown:
        engine.shutdown()
    return out


def _runner(engine):
    return engine.engine_core.engine_core.executor.worker.model_runner


@pytest.fixture(autouse=True)
def _restore_tpla_env():
    saved = os.environ.get("VDT_TPLA")
    yield
    if saved is None:
        os.environ.pop("VDT_TPLA", None)
    else:
        os.environ["VDT_TPLA"] = saved


@pytest.fixture(scope="module")
def baseline_tokens():
    """TP=1 replicated-layout greedy outputs (the parity reference; the
    dummy loader's seeded init gives every engine of this config
    identical weights)."""
    return run(make_engine(tp=1), "base")


# ---------------------------------------------------------------------------
# Parity matrix: TPLA-sharded vs replicated, 2- and 4-device meshes,
# XLA scan and interpret-mode Pallas.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("tp", [2, 4])
def test_tpla_token_identical_xla(baseline_tokens, tp, monkeypatch):
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "xla")
    engine = make_engine(tp=tp)
    assert _runner(engine).model.tpla_shards == tp
    assert set(_runner(engine).kv_caches) == {"c", "pe"}
    assert run(engine, f"tpla{tp}") == baseline_tokens


@pytest.mark.parametrize("tp", [2, 4])
def test_tpla_token_identical_pallas_interpret(baseline_tokens, tp,
                                               monkeypatch):
    # conftest sets VDT_PALLAS_INTERPRET=1; forcing the pallas backend
    # exercises the TPLA dispatch the real TPU path takes.
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    assert run(make_engine(tp=tp), f"tplap{tp}") == baseline_tokens


def test_replicated_pallas_kernel_still_token_identical(baseline_tokens,
                                                        monkeypatch):
    # VDT_TPLA=0 on the pallas backend keeps the per-rank latent KERNEL
    # serving the replicated cache — the revert leg of the matrix.
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    assert run(make_engine(tp=2, tpla=False), "repl2p") == baseline_tokens


def test_tpla_combine_rides_quantized_plane(baseline_tokens,
                                            monkeypatch):
    """VDT_QCOMM path "tpla" quantizes the per-layer W_UV output
    combine (greedy token parity at block 16, like the tknp/tp paths'
    e2e gates) and the trace counters record its savings. The score
    psum stays exact by design, so parity holds at toy scale."""
    from vllm_distributed_tpu.parallel import collectives
    monkeypatch.setenv("VDT_QCOMM", "1")
    monkeypatch.setenv("VDT_QCOMM_PATHS", "tpla")
    monkeypatch.setenv("VDT_QCOMM_BLOCK", "16")
    collectives.refresh()
    collectives.reset_counters()
    try:
        assert run(make_engine(tp=2), "qtpla") == baseline_tokens
        assert collectives.traced_snapshot()["bytes_saved"].get(
            "tpla", 0) > 0
    finally:
        collectives.refresh()


# ---------------------------------------------------------------------------
# VDT_TPLA=0 reverts wholesale to the replicated layout.
# ---------------------------------------------------------------------------
def test_tpla_off_reverts_to_replicated_layout(baseline_tokens):
    engine = make_engine(tp=2, tpla=False)
    runner = _runner(engine)
    assert runner.model.tpla_shards == 1
    assert set(runner.kv_caches) == {"c"}  # no rope sidecar
    from jax.sharding import PartitionSpec as P
    from vllm_distributed_tpu.config import MESH_AXIS_TOKEN
    assert runner.model.kv_cache_specs() == {
        "c": P(None, MESH_AXIS_TOKEN, None, None)}
    assert run(engine, "repl2") == baseline_tokens


def test_tpla_falls_back_when_lkv_indivisible():
    # kv_lora_rank=64 does not divide 3 ways... the loader cannot see a
    # TP=3 mesh on this 8-device pool (2x4 factorization only), so pin
    # the indivisible case directly at the arch level.
    from vllm_distributed_tpu.ops.mla import tpla_applicable
    assert not tpla_applicable(30, 4)
    assert tpla_applicable(64, 4)


# ---------------------------------------------------------------------------
# Capacity: per-rank latent pool page count scales ~TP x at a fixed HBM
# budget, through the worker's real sizing path and the new gauges.
# ---------------------------------------------------------------------------
def test_latent_pool_capacity_scales_with_tp(monkeypatch):
    from vllm_distributed_tpu.worker.worker import TPUWorker

    budget = 1 << 20  # 1 MiB fixed per-device HBM budget

    def pages_for(tp, tpla):
        cfg = make_config(tp=tp, tpla=tpla)
        cfg.cache_config.num_gpu_blocks_override = None
        worker = TPUWorker(cfg)
        worker.init_device()
        worker.load_model()
        monkeypatch.setattr(worker.model_runner, "profile_memory_bytes",
                            lambda: budget)
        return worker.determine_num_available_blocks()

    pages_repl = pages_for(2, False)
    pages_tpla2 = pages_for(2, True)
    pages_tpla4 = pages_for(4, True)
    # Geometry: replicated row = 64 + 8 = 72 lanes/page/rank; TPLA(2) =
    # 32 + 8 = 40; TPLA(4) = 16 + 8 = 24. The rope sidecar is the
    # replicated remainder, so the scaling is ~TP x, not exactly TP x.
    assert pages_tpla2 >= int(1.7 * pages_repl)
    assert pages_tpla4 >= int(2.8 * pages_repl)
    assert pages_tpla4 == budget // (3 * 4 * 24 * 4)  # L*PS*lanes*f32


def test_tpla_gauges_flow_to_metrics():
    from vllm_distributed_tpu.metrics.prometheus import render_metrics

    engine = make_engine(tp=2)
    try:
        stats = engine.get_stats()
        workers = stats.get("workers") or {}
        assert workers, "worker telemetry map missing from stats"
        entry = next(iter(workers.values()))
        assert entry["tpla_latent_shards"] == 2
        # 3 layers x page_size 4 x (32 + 8) lanes x 4 bytes.
        assert entry["mla_latent_page_bytes"] == 3 * 4 * 40 * 4
        text = render_metrics(stats)
        assert "vdt:tpla_latent_shards{" in text
        assert "vdt:mla_latent_page_bytes{" in text
        assert 'vdt:kv_blocks{state="free"}' in text
    finally:
        engine.shutdown()


# ---------------------------------------------------------------------------
# KV transfer: latent pages round-trip between meshes of DIFFERENT TP
# degree (TP=1 replicated producer -> TP=2 TPLA consumer) bit-exactly.
# ---------------------------------------------------------------------------
def test_latent_pages_transfer_across_tp_degrees(tmp_path,
                                                 baseline_tokens):
    storage = str(tmp_path / "kv")

    producer = make_engine(tp=1, storage=storage, role="kv_producer")
    assert run(producer, "prod") == baseline_tokens
    assert os.listdir(storage), "producer wrote no latent page files"

    consumer = make_engine(tp=2, tpla=True, storage=storage,
                           role="kv_consumer")
    core = consumer.engine_core.engine_core
    wc = core.executor.worker.model_runner.kv_connector
    out = run(consumer, "cons", shutdown=False)
    try:
        # Identical greedy continuations prove the externally-loaded
        # latent pages decoded bit-exactly into the TPLA-sharded cache
        # (raw wire format; VDT_QCOMM default off).
        assert out == baseline_tokens
        assert wc.num_pages_loaded > 0
    finally:
        consumer.shutdown()


def test_check_latent_wire_rejects_foreign_stores():
    """A same-geometry-but-deeper (or geometry-foreign) latent store
    must be REJECTED before any scatter — truncating another model's
    layer stack into the cache would be silent corruption."""
    import numpy as np

    from vllm_distributed_tpu.distributed.kv_transfer.page_io import \
        check_latent_wire

    class _Cfg:
        mla = True
        kv_lora_rank = 64
        qk_rope_head_dim = 8
        tpla_shards = 2
        num_layers = 3

    class _Runner:
        class model:
            cfg = _Cfg()

    r = _Runner()
    k = np.zeros((3, 2, 4, 64), np.float32)
    v = np.zeros((3, 2, 4, 8), np.float32)
    check_latent_wire(r, k, v)  # exact layout: accepted
    with pytest.raises(RuntimeError):  # deeper producer stack
        check_latent_wire(r, np.zeros((4, 2, 4, 64), np.float32),
                          np.zeros((4, 2, 4, 8), np.float32))
    with pytest.raises(RuntimeError):  # foreign latent width
        check_latent_wire(r, np.zeros((3, 2, 4, 32), np.float32), v)
    with pytest.raises(RuntimeError):  # meta disagrees with the model
        check_latent_wire(r, k, v, {"kv_lora_rank": 32, "rope_dim": 8})


@pytest.mark.parametrize("tp,tpla", [(2, True), (1, True), (2, False)])
def test_latent_stage_and_chunked_scatter_roundtrip(tp, tpla):
    """The dcn_pull staging path (stage_pages -> donated
    scatter_pages_chunk) must round-trip latent pages bit-exactly in
    every layout: TPLA-sharded, TP=1 replicated, and TP>1 replicated
    (VDT_TPLA=0)."""
    import jax.numpy as jnp
    import numpy as np

    from vllm_distributed_tpu.distributed.kv_transfer import page_io

    engine = make_engine(tp=tp, tpla=tpla)
    try:
        runner = _runner(engine)
        run(engine, f"warm{tp}{tpla}", shutdown=False)  # populate pages
        page_ids = [0, 1, 2]
        k_w, v_w = page_io.gather_pages(runner, page_ids)
        assert k_w.shape[-1] == 64 and v_w.shape[-1] == 8
        # Wipe the pages, then restore them through the staged +
        # chunked donated-scatter path the async pull uses.
        for key, arr in list(runner.kv_caches.items()):
            runner.kv_caches[key] = arr.at[:, jnp.asarray(page_ids)].set(0)
        k_dev, v_dev = page_io.stage_pages(runner, k_w, v_w)
        page_io.scatter_pages_chunk(runner, page_ids, k_dev, v_dev,
                                    lo=0, chunk=2)
        page_io.scatter_pages_chunk(runner, page_ids, k_dev, v_dev,
                                    lo=2, chunk=2)
        k_2, v_2 = page_io.gather_pages(runner, page_ids)
        assert np.array_equal(k_2, k_w)
        assert np.array_equal(v_2, v_w)
    finally:
        engine.shutdown()


def test_tpla_producer_feeds_replicated_consumer(tmp_path,
                                                 baseline_tokens):
    # The reverse asymmetry: a TPLA-sharded engine gathers FULL rows
    # into the store; a TP=1 replicated engine re-slices on receipt.
    storage = str(tmp_path / "kv")
    producer = make_engine(tp=2, tpla=True, storage=storage,
                           role="kv_producer")
    assert run(producer, "prod2") == baseline_tokens
    consumer = make_engine(tp=1, storage=storage, role="kv_consumer")
    core = consumer.engine_core.engine_core
    wc = core.executor.worker.model_runner.kv_connector
    out = run(consumer, "cons1", shutdown=False)
    try:
        assert out == baseline_tokens
        assert wc.num_pages_loaded > 0
    finally:
        consumer.shutdown()
