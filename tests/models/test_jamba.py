"""HF greedy parity for Jamba (hybrid attention/mamba/MoE) and its
hybrid cache-group accounting.

Reference pattern: tests/models/ per-arch correctness vs HfRunner for
vllm/model_executor/models/jamba.py.
"""

import pytest
import torch
from transformers import JambaConfig, JambaForCausalLM

from _engine_harness import PROMPTS, hf_greedy, run_engine as run
from vllm_distributed_tpu.engine.arg_utils import EngineArgs


@pytest.fixture(scope="module")
def jamba_ckpt(tmp_path_factory):
    """4 layers, attn at layer 2 (period 4 / offset 2), MoE on odd
    layers (period 2 / offset 1) — every block kind exercised."""
    torch.manual_seed(0)
    cfg = JambaConfig(vocab_size=128, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=4,
                      num_attention_heads=4, num_key_value_heads=2,
                      mamba_d_state=8, mamba_d_conv=4, mamba_expand=2,
                      mamba_dt_rank=4, attn_layer_period=4,
                      attn_layer_offset=2, expert_layer_period=2,
                      expert_layer_offset=1, num_experts=4,
                      num_experts_per_tok=2, max_position_embeddings=64,
                      eos_token_id=1, tie_word_embeddings=False,
                      use_mamba_kernels=False)
    hf = JambaForCausalLM(cfg)
    path = tmp_path_factory.mktemp("jamba-tiny")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf.eval()


def test_jamba_greedy_matches_hf(jamba_ckpt):
    path, hf = jamba_ckpt
    expect = [hf_greedy(hf, p, 6) for p in PROMPTS]
    got = run(path, PROMPTS)
    assert got == expect


def test_jamba_chunked_prefill_threads_state(jamba_ckpt):
    path, hf = jamba_ckpt
    long_prompt = [(i * 7 + 3) % 128 for i in range(40)]
    expect = [hf_greedy(hf, long_prompt, 6)]
    got = run(path, [long_prompt], max_num_batched_tokens=16,
              max_model_len=64)
    assert got == expect


def test_jamba_tp2_matches_single_chip(jamba_ckpt):
    path, hf = jamba_ckpt
    expect = [hf_greedy(hf, p, 6) for p in PROMPTS]
    got = run(path, PROMPTS, tensor_parallel_size=2)
    assert got == expect


@pytest.fixture(scope="module")
def bamba_ckpt(tmp_path_factory):
    """3 layers: mamba2 / attention(partial rotary) / mamba2."""
    from transformers import BambaConfig, BambaForCausalLM
    torch.manual_seed(4)
    cfg = BambaConfig(vocab_size=128, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=3,
                      num_attention_heads=4, num_key_value_heads=2,
                      attn_layer_indices=[1], mamba_n_heads=8,
                      mamba_d_head=8, mamba_n_groups=2, mamba_d_state=8,
                      mamba_d_conv=4, mamba_expand=2,
                      max_position_embeddings=64, eos_token_id=1,
                      tie_word_embeddings=False)
    hf = BambaForCausalLM(cfg)
    path = tmp_path_factory.mktemp("bamba-tiny")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf.eval()


def test_bamba_greedy_matches_hf(bamba_ckpt):
    path, hf = bamba_ckpt
    expect = [hf_greedy(hf, p, 6) for p in PROMPTS]
    got = run(path, PROMPTS)
    assert got == expect


def test_bamba_chunked_prefill_threads_state(bamba_ckpt):
    path, hf = bamba_ckpt
    long_prompt = [(i * 13 + 1) % 128 for i in range(40)]
    expect = [hf_greedy(hf, long_prompt, 6)]
    got = run(path, [long_prompt], max_num_batched_tokens=16,
              max_model_len=64)
    assert got == expect


def test_bamba_tp2_matches_single_chip(bamba_ckpt):
    path, hf = bamba_ckpt
    expect = [hf_greedy(hf, p, 6) for p in PROMPTS]
    got = run(path, PROMPTS, tensor_parallel_size=2)
    assert got == expect


def test_jamba_hybrid_cache_groups_charge_attn_only(jamba_ckpt):
    """Pages are charged for the ATTENTION layers only (1 of 4 here):
    the hybrid-group memory win of per-kind cache sizing (reference:
    v1/kv_cache_interface.py per-group page_size_bytes)."""
    path, _ = jamba_ckpt
    from vllm_distributed_tpu.models.loader import get_model
    from vllm_distributed_tpu.parallel.mesh import build_mesh
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=32, max_model_len=64,
                max_num_batched_tokens=32, max_num_seqs=4,
                skip_tokenizer_init=True)
    config = EngineArgs(**args).create_engine_config()
    mesh = build_mesh(config.parallel_config)
    model, _ = get_model(config, mesh)
    La = len(model._attn_layers)
    Lm = len(model._mamba_layers)
    assert (La, Lm) == (1, 3)
    # Page bytes scale with La only.
    full_kv = model.kv_cache_page_bytes(4)
    per_layer = full_kv // La
    assert full_kv == per_layer * La
    # State bytes cover the mamba layers and match the real arrays.
    caches = model.make_kv_caches(num_pages=8, page_size=4)
    assert caches["k"].shape[0] == La
    assert caches["conv"].shape[0] == Lm
    assert model.fixed_cache_bytes() == (caches["conv"].nbytes +
                                         caches["ssm"].nbytes)
