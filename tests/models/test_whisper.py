"""Whisper encoder-decoder through the paged engine (reference:
models/whisper.py + the transcription serving path): HF greedy parity
from mel features, cross-attention state rows surviving batching."""

import numpy as np
import pytest
import torch
import transformers

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


def _tiny_cfg():
    return transformers.WhisperConfig(
        vocab_size=64, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64, num_mel_bins=8,
        max_source_positions=16, max_target_positions=64,
        decoder_start_token_id=2, eos_token_id=1, pad_token_id=0)


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    torch.manual_seed(0)
    hf = transformers.WhisperForConditionalGeneration(_tiny_cfg()).eval()
    path = str(tmp_path_factory.mktemp("tiny_whisper"))
    hf.save_pretrained(path, safe_serialization=True)
    return path, hf


def hf_greedy(hf, mel, prompt, n):
    """Manual greedy loop (hf.generate applies suppression processors
    the engine intentionally does not)."""
    ids = list(prompt)
    feats = torch.tensor(mel, dtype=torch.float32)[None]
    with torch.no_grad():
        for _ in range(n):
            out = hf(input_features=feats,
                     decoder_input_ids=torch.tensor([ids]))
            ids.append(int(out.logits[0, -1].argmax()))
    return ids[len(prompt):]


def _make_engine(path, **overrides):
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def _run(engine, reqs, n=6):
    sp = SamplingParams(temperature=0.0, max_tokens=n, ignore_eos=True)
    for i, (prompt, mel) in enumerate(reqs):
        engine.add_request(f"w-{i}", prompt, sp,
                           multi_modal_data={"input_features": mel})
    done = {}
    for _ in range(200):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out.outputs[0].token_ids
        if not engine.has_unfinished_requests():
            break
    return [done[f"w-{i}"] for i in range(len(reqs))]


def test_whisper_greedy_matches_hf(ckpt):
    path, hf = ckpt
    rng = np.random.default_rng(0)
    mel = rng.standard_normal((8, 32)).astype(np.float32)
    prompt = [2, 5, 7]
    engine = _make_engine(path)
    got = _run(engine, [(prompt, mel)], n=6)[0]
    assert got == hf_greedy(hf, mel, prompt, 6)


def test_whisper_batched_audio_stays_per_request(ckpt):
    """Two concurrent requests with DIFFERENT audio must each attend
    their own cross-state row."""
    path, hf = ckpt
    rng = np.random.default_rng(1)
    mel_a = rng.standard_normal((8, 32)).astype(np.float32)
    mel_b = rng.standard_normal((8, 32)).astype(np.float32)
    engine = _make_engine(path)
    got = _run(engine, [([2, 5, 7], mel_a), ([2, 9], mel_b)], n=5)
    assert got[0] == hf_greedy(hf, mel_a, [2, 5, 7], 5)
    assert got[1] == hf_greedy(hf, mel_b, [2, 9], 5)


def test_whisper_audio_on_decoder_only_model_rejected(tmp_path_factory):
    from tests.models._engine_harness import run_engine
    from transformers import LlamaConfig
    from transformers import LlamaForCausalLM as HFLlama
    torch.manual_seed(2)
    hf = HFLlama(LlamaConfig(vocab_size=64, hidden_size=32,
                             intermediate_size=64, num_hidden_layers=1,
                             num_attention_heads=4,
                             num_key_value_heads=2,
                             max_position_embeddings=64))
    path = str(tmp_path_factory.mktemp("tiny_llama_noaudio"))
    hf.save_pretrained(path, safe_serialization=True)
    engine = _make_engine(path)
    with pytest.raises(ValueError, match="encoder-decoder"):
        engine.add_request(
            "a-0", [2, 5], SamplingParams(max_tokens=2),
            multi_modal_data={"input_features": np.zeros((8, 32),
                                                         np.float32)})


def test_whisper_request_without_audio_rejected(ckpt):
    """An enc-dec request with no encoder payload must 400 at admission:
    admitted, it would cross-attend to whatever audio a reused batch row
    last held (cross-request leakage)."""
    path, _ = ckpt
    engine = _make_engine(path)
    with pytest.raises(ValueError, match="requires an encoder input"):
        engine.add_request("noaud-0", [2, 5, 7],
                           SamplingParams(temperature=0.0, max_tokens=2))


def test_whisper_tp2_matches_single_device(ckpt):
    """Cross-attention state rows + head sharding under GSPMD TP."""
    path, _ = ckpt
    rng = np.random.default_rng(3)
    mel = rng.standard_normal((8, 32)).astype(np.float32)
    single = _make_engine(path)
    tp2 = _make_engine(path, tensor_parallel_size=2)
    a = _run(single, [([2, 5, 7], mel)], n=5)[0]
    b = _run(tp2, [([2, 5, 7], mel)], n=5)[0]
    assert a == b
