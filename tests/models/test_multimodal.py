"""Multimodal minimum slice: llava-style image+text generation with
pre-computed projector embeddings, HF parity, encoder-cache budgeting,
and prefix-cache safety (reference: vllm/multimodal/ +
v1/core/encoder_cache_manager.py)."""

import json
import os

import numpy as np
import pytest
import torch
from transformers import (CLIPVisionConfig, LlamaConfig, LlavaConfig,
                          LlavaForConditionalGeneration)

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

IMG = 99  # image_token_index


@pytest.fixture(scope="module")
def llava_checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlavaConfig(
        text_config=LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=128),
        vision_config=CLIPVisionConfig(
            hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=2, image_size=16, patch_size=8,
            projection_dim=32),
        image_token_index=IMG)
    hf = LlavaForConditionalGeneration(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llava")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=128,
                max_num_batched_tokens=128, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def run(engine, jobs, tag, max_tokens=6):
    """jobs: list of (prompt_ids, mm_dict_or_None)."""
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                        ignore_eos=True)
    for i, (p, mm) in enumerate(jobs):
        engine.add_request(f"{tag}-{i}", p, sp, multi_modal_data=mm)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k].outputs[0].token_ids for k in order]


def _features(hf, pixel) -> np.ndarray:
    with torch.no_grad():
        (feats, ) = hf.get_image_features(pixel)  # [n_tokens, H]
    return feats.numpy()


def test_llava_image_prompt_matches_hf(llava_checkpoint):
    """LLM-level e2e: prompt with ONE placeholder + projector embeddings
    must match HF llava generate with pixel_values exactly."""
    path, hf = llava_checkpoint
    torch.manual_seed(1)
    pixel = torch.randn(1, 3, 16, 16)
    feats = _features(hf, pixel)
    n_img = feats.shape[0]

    prompt = [3, 17, IMG, 45, 8]
    # HF wants the placeholder pre-expanded to n_img tokens.
    hf_ids = [3, 17] + [IMG] * n_img + [45, 8]
    with torch.no_grad():
        hf_out = hf.generate(
            input_ids=torch.tensor([hf_ids]), pixel_values=pixel,
            max_new_tokens=6, do_sample=False)
    want = hf_out[0].tolist()[len(hf_ids):]

    engine = make_engine(path)
    (got, ) = run(engine, [(prompt, {"image_embeds": feats})], "mm")
    assert got == want


def test_text_only_requests_still_work(llava_checkpoint):
    path, hf = llava_checkpoint
    prompt = [3, 17, 45, 8, 21]
    with torch.no_grad():
        hf_out = hf.generate(input_ids=torch.tensor([prompt]),
                             max_new_tokens=6, do_sample=False)
    want = hf_out[0].tolist()[len(prompt):]
    (got, ) = run(make_engine(path), [(prompt, None)], "txt")
    assert got == want


def test_mixed_batch_and_two_images(llava_checkpoint):
    """Text and image requests in one batch; a prompt with two images."""
    path, hf = llava_checkpoint
    torch.manual_seed(2)
    pix = torch.randn(2, 3, 16, 16)
    with torch.no_grad():
        f1, f2 = (f.numpy() for f in hf.get_image_features(pix))
    n = f1.shape[0]
    p2 = [5, IMG, 9, IMG, 11]
    hf_ids = [5] + [IMG] * n + [9] + [IMG] * n + [11]
    with torch.no_grad():
        hf_out = hf.generate(input_ids=torch.tensor([hf_ids]),
                             pixel_values=pix, max_new_tokens=5,
                             do_sample=False)
    want2 = hf_out[0].tolist()[len(hf_ids):]

    engine = make_engine(path)
    got = run(engine, [
        ([3, 17, 45], None),
        (p2, {"image_embeds": [f1, f2]}),
    ], "mix", max_tokens=5)
    assert got[1] == want2


def test_different_images_never_share_prefix_cache(llava_checkpoint):
    """Identical expanded token ids with DIFFERENT images must not hit
    each other's prefix-cache pages (the mm content hash salts the
    block-hash chain)."""
    path, hf = llava_checkpoint
    torch.manual_seed(3)
    pix = torch.randn(2, 3, 16, 16)
    with torch.no_grad():
        f1, f2 = (f.numpy() for f in hf.get_image_features(pix))
    prompt = [IMG, 45, 8]

    engine = make_engine(path)
    (a, ) = run(engine, [(prompt, {"image_embeds": f1})], "pc1")
    (b, ) = run(engine, [(prompt, {"image_embeds": f2})], "pc2")
    # Fresh engine, no cache: ground truth per image.
    (a0, ) = run(make_engine(path), [(prompt, {"image_embeds": f1})],
                 "pc3")
    (b0, ) = run(make_engine(path), [(prompt, {"image_embeds": f2})],
                 "pc4")
    assert a == a0
    assert b == b0


def test_encoder_budget_queues_image_requests(llava_checkpoint):
    """Requests past the encoder-token budget wait instead of
    overcommitting; they complete once earlier image requests free."""
    path, hf = llava_checkpoint
    torch.manual_seed(4)
    pixel = torch.randn(1, 3, 16, 16)
    feats = _features(hf, pixel)
    n = feats.shape[0]
    engine = make_engine(path, encoder_cache_budget=n)  # one image max
    sched = engine.engine_core.engine_core.scheduler
    jobs = [([3, IMG, 45 + i], {"image_embeds": feats})
            for i in range(3)]
    got = run(engine, jobs, "bud")
    assert len(got) == 3
    assert sched.encoder_cache.used == 0  # all freed


def test_oversized_image_request_rejected(llava_checkpoint):
    """A request that could never fit the encoder budget is a client
    error at admission, not a silent queue-head deadlock."""
    path, hf = llava_checkpoint
    torch.manual_seed(5)
    feats = _features(hf, torch.randn(1, 3, 16, 16))
    engine = make_engine(path, encoder_cache_budget=1)
    sp = SamplingParams(temperature=0.0, max_tokens=4)
    with pytest.raises(ValueError, match="encoder_cache_budget"):
        engine.add_request("big-0", [3, IMG, 45], sp,
                           multi_modal_data={"image_embeds": feats})


def test_mm_request_survives_zmq_serialization():
    """The msgpack boundary (multiprocess engine core) round-trips the
    embedding payloads bit-exactly."""
    from vllm_distributed_tpu.engine.serial import (decode_request,
                                                    encode_request, pack,
                                                    unpack)
    from vllm_distributed_tpu.multimodal import MultiModalInput
    from vllm_distributed_tpu.request import EngineCoreRequest
    emb = np.random.default_rng(0).standard_normal((4, 8)).astype(
        np.float32)
    req = EngineCoreRequest(
        request_id="mm-1", prompt_token_ids=[1, IMG, IMG, IMG, IMG, 2],
        sampling_params=SamplingParams(max_tokens=4),
        mm_inputs=[MultiModalInput(embeds=emb, offset=1)])
    back = decode_request(unpack(pack(encode_request(req))))
    assert back.mm_inputs is not None and len(back.mm_inputs) == 1
    assert back.mm_inputs[0].offset == 1
    np.testing.assert_array_equal(back.mm_inputs[0].embeds, emb)


def test_subblock_mm_prompt_does_not_poison_prefix_cache(llava_checkpoint):
    """An expanded mm prompt SHORTER than one block starts with an empty
    hash list; the chain restarted during decode must still carry the
    image salt (code-review r4 finding) — different images with
    identical token ids must never share pages."""
    path, hf = llava_checkpoint
    torch.manual_seed(6)
    pix = torch.randn(2, 3, 16, 16)
    with torch.no_grad():
        f1, f2 = (f.numpy() for f in hf.get_image_features(pix))
    prompt = [IMG, 45]  # expands to 6 tokens < block_size 8

    engine = make_engine(path, block_size=8)
    (a, ) = run(engine, [(prompt, {"image_embeds": f1})], "sb1",
                max_tokens=10)
    (b, ) = run(engine, [(prompt, {"image_embeds": f2})], "sb2",
                max_tokens=10)
    (a0, ) = run(make_engine(path, block_size=8),
                 [(prompt, {"image_embeds": f1})], "sb3", max_tokens=10)
    (b0, ) = run(make_engine(path, block_size=8),
                 [(prompt, {"image_embeds": f2})], "sb4", max_tokens=10)
    assert a == a0
    assert b == b0


def test_pixel_values_through_in_engine_vision_tower(llava_checkpoint):
    """PIXELS in: the in-engine CLIP tower + projector (multimodal/
    vision.py) must reproduce HF llava generate from raw pixel_values —
    no client-side feature extraction."""
    path, hf = llava_checkpoint
    torch.manual_seed(7)
    pixel = torch.randn(1, 3, 16, 16)
    n_img = _features(hf, pixel).shape[0]
    prompt = [3, 17, IMG, 45, 8]
    hf_ids = [3, 17] + [IMG] * n_img + [45, 8]
    with torch.no_grad():
        hf_out = hf.generate(
            input_ids=torch.tensor([hf_ids]), pixel_values=pixel,
            max_new_tokens=6, do_sample=False)
    want = hf_out[0].tolist()[len(hf_ids):]

    engine = make_engine(path)
    (got, ) = run(engine, [(prompt,
                            {"pixel_values": pixel.numpy()})], "pix")
    assert got == want


def test_image_preprocessing_matches_hf_clip_processor(tmp_path):
    """Our preprocessor_config-driven pipeline matches transformers'
    CLIPImageProcessor output."""
    from PIL import Image
    from transformers import CLIPImageProcessor

    from vllm_distributed_tpu.multimodal.image_processing import \
        ImagePreprocessor
    rng = np.random.default_rng(0)
    img = Image.fromarray(
        rng.integers(0, 255, size=(40, 56, 3), dtype=np.uint8))
    hf_proc = CLIPImageProcessor(size={"shortest_edge": 16},
                                 crop_size={"height": 16, "width": 16})
    hf_proc.save_pretrained(tmp_path)

    class HFC:
        class vision_config:
            image_size = 16
    ours = ImagePreprocessor(str(tmp_path), HFC)
    got = ours(img)
    want = hf_proc(img, return_tensors="np")["pixel_values"][0]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_openai_chat_accepts_data_url_images(llava_checkpoint,
                                             tmp_path_factory):
    """OpenAI chat completions with an image_url content part: the
    server decodes + preprocesses the image, inserts the placeholder
    token, and matches the offline engine fed the same pixels."""
    import asyncio
    import base64
    import io
    import threading
    import urllib.request

    from PIL import Image
    from tokenizers import Tokenizer, models, pre_tokenizers
    from transformers import CLIPImageProcessor, PreTrainedTokenizerFast

    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.multimodal.image_processing import \
        ImagePreprocessor
    from vllm_distributed_tpu.utils import get_open_port

    path, hf = llava_checkpoint
    served = str(tmp_path_factory.mktemp("llava_served"))
    import shutil
    for f in os.listdir(path):
        shutil.copy(os.path.join(path, f), served)
    # Tokenizer with the image placeholder in-vocab.
    vocab = {f"w{i}": i for i in range(128)}
    vocab.update({"<image>": IMG, "hello": 3, "cat": 17, "dog": 45,
                  "<unk>": 126, "</s>": 1})
    vocab = {k: v for k, v in vocab.items()
             if list(vocab.values()).count(v) == 1 or not k.startswith("w")}
    tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    # The placeholder must tokenize ATOMICALLY (real llava tokenizers
    # register <image> as an added special token).
    PreTrainedTokenizerFast(
        tokenizer_object=tok, unk_token="<unk>", eos_token="</s>",
        additional_special_tokens=["<image>"]).save_pretrained(served)
    CLIPImageProcessor(size={"shortest_edge": 16},
                       crop_size={"height": 16,
                                  "width": 16}).save_pretrained(served)

    engine_args = EngineArgs(model=served, dtype="float32", block_size=4,
                             num_gpu_blocks_override=128,
                             max_model_len=128,
                             max_num_batched_tokens=128, max_num_seqs=8)
    engine = AsyncLLM(engine_args.create_engine_config())
    port = get_open_port()
    ready = threading.Event()
    stop_holder = {}

    def serve_thread():
        from vllm_distributed_tpu.entrypoints.openai.api_server import \
            serve
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        stop = asyncio.Event()
        stop_holder.update(stop=stop, loop=loop)
        loop.run_until_complete(serve(engine, served, "127.0.0.1", port,
                                      ready_event=ready,
                                      stop_event=stop))
        loop.close()

    t = threading.Thread(target=serve_thread, daemon=True)
    t.start()
    assert ready.wait(timeout=180)
    try:
        rng = np.random.default_rng(9)
        img = Image.fromarray(
            rng.integers(0, 255, size=(16, 16, 3), dtype=np.uint8))
        buf = io.BytesIO()
        img.save(buf, format="PNG")
        url = ("data:image/png;base64," +
               base64.b64encode(buf.getvalue()).decode())
        body = json.dumps({
            "model": "m",
            "messages": [{"role": "user", "content": [
                {"type": "text", "text": "hello "},
                {"type": "image_url", "image_url": {"url": url}},
                {"type": "text", "text": " cat"},
            ]}],
            "max_tokens": 5, "temperature": 0.0,
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            resp = json.loads(r.read())
        text = resp["choices"][0]["message"]["content"]
        assert resp["choices"][0]["finish_reason"] in ("length", "stop")

        # Offline reference: same pixels through the same preprocessor
        # and the template-less chat transcript.

        class HFC:
            class vision_config:
                image_size = 16
        pix = ImagePreprocessor(served, HFC)(img)
        from vllm_distributed_tpu.engine.llm_engine import LLMEngine
        off = LLMEngine(EngineArgs(
            model=served, dtype="float32", block_size=4,
            num_gpu_blocks_override=128, max_model_len=128,
            max_num_batched_tokens=128,
            max_num_seqs=8).create_engine_config())
        tokenizer = off.processor.tokenizer
        prompt = tokenizer.encode("user: hello <image>  cat\nassistant:")
        off.add_request("off-0", prompt,
                        SamplingParams(temperature=0.0, max_tokens=5),
                        multi_modal_data={"pixel_values": [pix]})
        outs = []
        for _ in range(200):
            outs += [o for o in off.step() if o.finished]
            if outs:
                break
        want = tokenizer.decode(outs[0].outputs[0].token_ids)
        assert text == want, (text, want)
    finally:
        stop_holder["loop"].call_soon_threadsafe(stop_holder["stop"].set)
        t.join(timeout=30)
