"""Multimodal minimum slice: llava-style image+text generation with
pre-computed projector embeddings, HF parity, encoder-cache budgeting,
and prefix-cache safety (reference: vllm/multimodal/ +
v1/core/encoder_cache_manager.py)."""

import numpy as np
import pytest
import torch
from transformers import (CLIPVisionConfig, LlamaConfig, LlavaConfig,
                          LlavaForConditionalGeneration)

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

IMG = 99  # image_token_index


@pytest.fixture(scope="module")
def llava_checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlavaConfig(
        text_config=LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=128),
        vision_config=CLIPVisionConfig(
            hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=2, image_size=16, patch_size=8,
            projection_dim=32),
        image_token_index=IMG)
    hf = LlavaForConditionalGeneration(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llava")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=128,
                max_num_batched_tokens=128, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def run(engine, jobs, tag, max_tokens=6):
    """jobs: list of (prompt_ids, mm_dict_or_None)."""
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                        ignore_eos=True)
    for i, (p, mm) in enumerate(jobs):
        engine.add_request(f"{tag}-{i}", p, sp, multi_modal_data=mm)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k].outputs[0].token_ids for k in order]


def _features(hf, pixel) -> np.ndarray:
    with torch.no_grad():
        (feats, ) = hf.get_image_features(pixel)  # [n_tokens, H]
    return feats.numpy()


def test_llava_image_prompt_matches_hf(llava_checkpoint):
    """LLM-level e2e: prompt with ONE placeholder + projector embeddings
    must match HF llava generate with pixel_values exactly."""
    path, hf = llava_checkpoint
    torch.manual_seed(1)
    pixel = torch.randn(1, 3, 16, 16)
    feats = _features(hf, pixel)
    n_img = feats.shape[0]

    prompt = [3, 17, IMG, 45, 8]
    # HF wants the placeholder pre-expanded to n_img tokens.
    hf_ids = [3, 17] + [IMG] * n_img + [45, 8]
    with torch.no_grad():
        hf_out = hf.generate(
            input_ids=torch.tensor([hf_ids]), pixel_values=pixel,
            max_new_tokens=6, do_sample=False)
    want = hf_out[0].tolist()[len(hf_ids):]

    engine = make_engine(path)
    (got, ) = run(engine, [(prompt, {"image_embeds": feats})], "mm")
    assert got == want


def test_text_only_requests_still_work(llava_checkpoint):
    path, hf = llava_checkpoint
    prompt = [3, 17, 45, 8, 21]
    with torch.no_grad():
        hf_out = hf.generate(input_ids=torch.tensor([prompt]),
                             max_new_tokens=6, do_sample=False)
    want = hf_out[0].tolist()[len(prompt):]
    (got, ) = run(make_engine(path), [(prompt, None)], "txt")
    assert got == want


def test_mixed_batch_and_two_images(llava_checkpoint):
    """Text and image requests in one batch; a prompt with two images."""
    path, hf = llava_checkpoint
    torch.manual_seed(2)
    pix = torch.randn(2, 3, 16, 16)
    with torch.no_grad():
        f1, f2 = (f.numpy() for f in hf.get_image_features(pix))
    n = f1.shape[0]
    p2 = [5, IMG, 9, IMG, 11]
    hf_ids = [5] + [IMG] * n + [9] + [IMG] * n + [11]
    with torch.no_grad():
        hf_out = hf.generate(input_ids=torch.tensor([hf_ids]),
                             pixel_values=pix, max_new_tokens=5,
                             do_sample=False)
    want2 = hf_out[0].tolist()[len(hf_ids):]

    engine = make_engine(path)
    got = run(engine, [
        ([3, 17, 45], None),
        (p2, {"image_embeds": [f1, f2]}),
    ], "mix", max_tokens=5)
    assert got[1] == want2


def test_different_images_never_share_prefix_cache(llava_checkpoint):
    """Identical expanded token ids with DIFFERENT images must not hit
    each other's prefix-cache pages (the mm content hash salts the
    block-hash chain)."""
    path, hf = llava_checkpoint
    torch.manual_seed(3)
    pix = torch.randn(2, 3, 16, 16)
    with torch.no_grad():
        f1, f2 = (f.numpy() for f in hf.get_image_features(pix))
    prompt = [IMG, 45, 8]

    engine = make_engine(path)
    (a, ) = run(engine, [(prompt, {"image_embeds": f1})], "pc1")
    (b, ) = run(engine, [(prompt, {"image_embeds": f2})], "pc2")
    # Fresh engine, no cache: ground truth per image.
    (a0, ) = run(make_engine(path), [(prompt, {"image_embeds": f1})],
                 "pc3")
    (b0, ) = run(make_engine(path), [(prompt, {"image_embeds": f2})],
                 "pc4")
    assert a == a0
    assert b == b0


def test_encoder_budget_queues_image_requests(llava_checkpoint):
    """Requests past the encoder-token budget wait instead of
    overcommitting; they complete once earlier image requests free."""
    path, hf = llava_checkpoint
    torch.manual_seed(4)
    pixel = torch.randn(1, 3, 16, 16)
    feats = _features(hf, pixel)
    n = feats.shape[0]
    engine = make_engine(path, encoder_cache_budget=n)  # one image max
    sched = engine.engine_core.engine_core.scheduler
    jobs = [([3, IMG, 45 + i], {"image_embeds": feats})
            for i in range(3)]
    got = run(engine, jobs, "bud")
    assert len(got) == 3
    assert sched.encoder_cache.used == 0  # all freed


def test_oversized_image_request_rejected(llava_checkpoint):
    """A request that could never fit the encoder budget is a client
    error at admission, not a silent queue-head deadlock."""
    path, hf = llava_checkpoint
    torch.manual_seed(5)
    feats = _features(hf, torch.randn(1, 3, 16, 16))
    engine = make_engine(path, encoder_cache_budget=1)
    sp = SamplingParams(temperature=0.0, max_tokens=4)
    with pytest.raises(ValueError, match="encoder_cache_budget"):
        engine.add_request("big-0", [3, IMG, 45], sp,
                           multi_modal_data={"image_embeds": feats})


def test_mm_request_survives_zmq_serialization():
    """The msgpack boundary (multiprocess engine core) round-trips the
    embedding payloads bit-exactly."""
    from vllm_distributed_tpu.engine.serial import (decode_request,
                                                    encode_request, pack,
                                                    unpack)
    from vllm_distributed_tpu.multimodal import MultiModalInput
    from vllm_distributed_tpu.request import EngineCoreRequest
    emb = np.random.default_rng(0).standard_normal((4, 8)).astype(
        np.float32)
    req = EngineCoreRequest(
        request_id="mm-1", prompt_token_ids=[1, IMG, IMG, IMG, IMG, 2],
        sampling_params=SamplingParams(max_tokens=4),
        mm_inputs=[MultiModalInput(embeds=emb, offset=1)])
    back = decode_request(unpack(pack(encode_request(req))))
    assert back.mm_inputs is not None and len(back.mm_inputs) == 1
    assert back.mm_inputs[0].offset == 1
    np.testing.assert_array_equal(back.mm_inputs[0].embeds, emb)


def test_subblock_mm_prompt_does_not_poison_prefix_cache(llava_checkpoint):
    """An expanded mm prompt SHORTER than one block starts with an empty
    hash list; the chain restarted during decode must still carry the
    image salt (code-review r4 finding) — different images with
    identical token ids must never share pages."""
    path, hf = llava_checkpoint
    torch.manual_seed(6)
    pix = torch.randn(2, 3, 16, 16)
    with torch.no_grad():
        f1, f2 = (f.numpy() for f in hf.get_image_features(pix))
    prompt = [IMG, 45]  # expands to 6 tokens < block_size 8

    engine = make_engine(path, block_size=8)
    (a, ) = run(engine, [(prompt, {"image_embeds": f1})], "sb1",
                max_tokens=10)
    (b, ) = run(engine, [(prompt, {"image_embeds": f2})], "sb2",
                max_tokens=10)
    (a0, ) = run(make_engine(path, block_size=8),
                 [(prompt, {"image_embeds": f1})], "sb3", max_tokens=10)
    (b0, ) = run(make_engine(path, block_size=8),
                 [(prompt, {"image_embeds": f2})], "sb4", max_tokens=10)
    assert a == a0
    assert b == b0


def test_pixel_values_through_in_engine_vision_tower(llava_checkpoint):
    """PIXELS in: the in-engine CLIP tower + projector (multimodal/
    vision.py) must reproduce HF llava generate from raw pixel_values —
    no client-side feature extraction."""
    path, hf = llava_checkpoint
    torch.manual_seed(7)
    pixel = torch.randn(1, 3, 16, 16)
    n_img = _features(hf, pixel).shape[0]
    prompt = [3, 17, IMG, 45, 8]
    hf_ids = [3, 17] + [IMG] * n_img + [45, 8]
    with torch.no_grad():
        hf_out = hf.generate(
            input_ids=torch.tensor([hf_ids]), pixel_values=pixel,
            max_new_tokens=6, do_sample=False)
    want = hf_out[0].tolist()[len(hf_ids):]

    engine = make_engine(path)
    (got, ) = run(engine, [(prompt,
                            {"pixel_values": pixel.numpy()})], "pix")
    assert got == want
