"""Llama parity vs HuggingFace transformers (model: reference
tests/conftest.py HfRunner ground-truth comparison, SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.models.common import AttentionBatch
from vllm_distributed_tpu.models.llama import (LlamaArchConfig,
                                               LlamaForCausalLM)

PAGE_SIZE = 4
NUM_PAGES = 32


def tiny_hf_config(**overrides):
    cfg = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
               num_hidden_layers=3, num_attention_heads=4,
               num_key_value_heads=2, max_position_embeddings=64,
               rope_theta=10000.0, tie_word_embeddings=False)
    cfg.update(overrides)
    return LlamaConfig(**cfg)


@pytest.fixture(scope="module")
def hf_model():
    torch.manual_seed(0)
    return HFLlama(tiny_hf_config()).eval()


@pytest.fixture(scope="module")
def jax_model_and_params(hf_model):
    arch = LlamaArchConfig.from_hf_config(hf_model.config,
                                          dtype=jnp.float32)
    model = LlamaForCausalLM(arch)
    tensors = {k: v.detach().numpy() for k, v in
               hf_model.state_dict().items()}
    params = model.params_from_hf_state_dict(tensors)
    return model, params


def run_ours(model, params, token_ids, *, positions=None, kv_caches=None,
             block_table=(1, 2, 3, 4)):
    """Single-request helper: prefill/decode token_ids at positions."""
    T = len(token_ids)
    if positions is None:
        positions = list(range(T))
    if kv_caches is None:
        kv_caches = model.make_kv_caches(NUM_PAGES, PAGE_SIZE)
    bt = np.zeros((1, max(8, len(block_table))), np.int32)
    bt[0, :len(block_table)] = block_table
    slot = [bt[0, p // PAGE_SIZE] * PAGE_SIZE + p % PAGE_SIZE
            for p in positions]
    batch = AttentionBatch(
        req_idx=jnp.zeros((T, ), jnp.int32),
        positions=jnp.asarray(positions, jnp.int32),
        slot_mapping=jnp.asarray(slot, jnp.int32),
        block_tables=jnp.asarray(bt),
        seq_lens=jnp.asarray([positions[-1] + 1], jnp.int32),
    )
    hidden, kv_caches = model.forward(params, kv_caches,
                                      jnp.asarray(token_ids, jnp.int32),
                                      batch)
    logits = model.compute_logits(params, hidden)
    return np.asarray(logits), kv_caches


def test_prefill_logits_match_hf(hf_model, jax_model_and_params):
    model, params = jax_model_and_params
    prompt = [3, 17, 92, 45, 8, 77, 23, 55, 10]
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor([prompt])).logits[0].numpy()
    ours, _ = run_ours(model, params, prompt)
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def test_chunked_prefill_plus_decode_matches_full(hf_model,
                                                  jax_model_and_params):
    """Prefill in two chunks then decode one token; logits must match a
    single-shot HF forward over the whole sequence."""
    model, params = jax_model_and_params
    seq = [5, 9, 101, 33, 2, 64, 18, 120, 7, 81, 44]
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor([seq])).logits[0].numpy()

    kv = model.make_kv_caches(NUM_PAGES, PAGE_SIZE)
    out1, kv = run_ours(model, params, seq[:6], positions=list(range(6)),
                        kv_caches=kv)
    out2, kv = run_ours(model, params, seq[6:10],
                        positions=list(range(6, 10)), kv_caches=kv)
    out3, kv = run_ours(model, params, seq[10:],
                        positions=[10], kv_caches=kv)
    np.testing.assert_allclose(out1[-1], hf_logits[5], rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(out2[-1], hf_logits[9], rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(out3[-1], hf_logits[10], rtol=2e-4,
                               atol=2e-4)


def test_greedy_generation_matches_hf(hf_model, jax_model_and_params):
    model, params = jax_model_and_params
    prompt = [11, 29, 3, 47]
    steps = 8
    with torch.no_grad():
        hf_out = hf_model.generate(torch.tensor([prompt]),
                                   max_new_tokens=steps, do_sample=False)
    hf_tokens = hf_out[0].tolist()[len(prompt):]

    kv = model.make_kv_caches(NUM_PAGES, PAGE_SIZE)
    logits, kv = run_ours(model, params, prompt, kv_caches=kv)
    ours = []
    tok = int(logits[-1].argmax())
    ours.append(tok)
    pos = len(prompt)
    for _ in range(steps - 1):
        logits, kv = run_ours(model, params, [tok], positions=[pos],
                              kv_caches=kv)
        tok = int(logits[-1].argmax())
        ours.append(tok)
        pos += 1
    assert ours == hf_tokens


def test_qwen2_style_attention_bias():
    torch.manual_seed(1)
    hf = HFLlama(tiny_hf_config(attention_bias=True)).eval()
    arch = LlamaArchConfig.from_hf_config(hf.config, dtype=jnp.float32)
    assert arch.attention_bias
    model = LlamaForCausalLM(arch)
    tensors = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    params = model.params_from_hf_state_dict(tensors)
    prompt = [4, 9, 2, 61, 33]
    with torch.no_grad():
        hf_logits = hf(torch.tensor([prompt])).logits[0].numpy()
    ours, _ = run_ours(model, params, prompt)
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def test_tied_embeddings():
    torch.manual_seed(2)
    hf = HFLlama(tiny_hf_config(tie_word_embeddings=True)).eval()
    arch = LlamaArchConfig.from_hf_config(hf.config, dtype=jnp.float32)
    model = LlamaForCausalLM(arch)
    tensors = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    params = model.params_from_hf_state_dict(tensors)
    prompt = [1, 2, 3, 4, 5]
    with torch.no_grad():
        hf_logits = hf(torch.tensor([prompt])).logits[0].numpy()
    ours, _ = run_ours(model, params, prompt)
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def test_llama31_rope_scaling():
    torch.manual_seed(3)
    scaling = {"rope_type": "llama3", "factor": 8.0,
               "low_freq_factor": 1.0, "high_freq_factor": 4.0,
               "original_max_position_embeddings": 32}
    hf = HFLlama(tiny_hf_config(rope_scaling=scaling,
                                max_position_embeddings=256)).eval()
    arch = LlamaArchConfig.from_hf_config(hf.config, dtype=jnp.float32)
    model = LlamaForCausalLM(arch)
    tensors = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    params = model.params_from_hf_state_dict(tensors)
    prompt = list(range(40, 80))  # long enough to engage scaled freqs
    with torch.no_grad():
        hf_logits = hf(torch.tensor([prompt])).logits[0].numpy()
    ours, _ = run_ours(model, params, prompt,
                       block_table=tuple(range(1, 11)))
    np.testing.assert_allclose(ours, hf_logits, rtol=3e-4, atol=3e-4)
