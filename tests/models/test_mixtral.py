"""Tiny-Mixtral parity vs HF through the full engine — MoE routing,
expert FFNs, and expert parallelism on the CPU mesh (model: reference
tests/models/ + tests/distributed/test_expert_parallel.py)."""

import numpy as np
import pytest
import torch
from transformers import MixtralConfig
from transformers import MixtralForCausalLM as HFMixtral

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = MixtralConfig(vocab_size=128, hidden_size=64,
                        intermediate_size=96, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        num_local_experts=4, num_experts_per_tok=2,
                        max_position_embeddings=64, eos_token_id=1)
    hf = HFMixtral(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_mixtral")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def hf_greedy(hf, prompt, n):
    with torch.no_grad():
        out = hf.generate(torch.tensor([prompt]), max_new_tokens=n,
                          do_sample=False, eos_token_id=None)
    return out[0].tolist()[len(prompt):]


PROMPTS = [
    [3, 17, 92, 45, 8],
    [5, 9, 33, 71],
    [11, 12, 13, 14, 15, 16],
]


def run(engine, prompts, tag, max_tokens=6):
    sps = [SamplingParams(temperature=0.0, max_tokens=max_tokens,
                          ignore_eos=True) for _ in prompts]
    for i, (p, sp) in enumerate(zip(prompts, sps)):
        engine.add_request(f"{tag}-{i}", p, sp)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k].outputs[0].token_ids for k in order]


def test_mixtral_greedy_matches_hf(checkpoint):
    path, hf = checkpoint
    got = run(make_engine(path), PROMPTS, "mx")
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_mixtral_expert_parallel_matches_hf(checkpoint):
    """Experts sharded over the model axis (EP spans the TP group)."""
    path, hf = checkpoint
    got = run(make_engine(path, tensor_parallel_size=4,
                          enable_expert_parallel=True), PROMPTS, "mxep")
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_mixtral_tp_inside_experts_matches_hf(checkpoint):
    """Without EP: Megatron TP inside each expert's FFN."""
    path, hf = checkpoint
    got = run(make_engine(path, tensor_parallel_size=2), PROMPTS, "mxtp")
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_mixtral_prefill_logprobs_match_hf(checkpoint):
    """Prefill logprob parity (tighter than greedy tokens): the engine's
    top-k logprobs on the first generated position must match HF's
    log-softmax over the last prompt position."""
    path, hf = checkpoint
    engine = make_engine(path)
    prompt = PROMPTS[0]
    k = 5
    engine.add_request("lg-0", prompt,
                       SamplingParams(temperature=0.0, max_tokens=1,
                                      ignore_eos=True, logprobs=k))
    outs = []
    for _ in range(50):
        outs += [o for o in engine.step() if o.finished]
        if not engine.has_unfinished_requests():
            break
    (out, ) = outs
    got = out.outputs[0].logprobs[0]  # dict[token_id, logprob]
    with torch.no_grad():
        hf_logits = hf(torch.tensor([prompt])).logits[0, -1]
    hf_lp = torch.log_softmax(hf_logits.float(), dim=-1)
    want_vals, want_ids = torch.topk(hf_lp, k)
    assert set(got) >= set(want_ids.tolist())
    for tok, val in zip(want_ids.tolist(), want_vals.tolist()):
        assert abs(got[tok] - val) < 5e-3, (tok, got[tok], val)


def test_moe_ragged_dispatch_cuts_flops(checkpoint, monkeypatch):
    """The grouped ragged_dot dispatch must cost ~k/E of the all-expert
    einsum baseline (VERDICT: 'counted-FLOPs test showing ~E/k cost
    reduction vs the einsum path'). Measured via XLA cost analysis on
    the jitted MoE block with E=8, k=2 -> expect <= ~0.5x, ideal 0.25x."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from vllm_distributed_tpu import envs
    from vllm_distributed_tpu.models.llama import LlamaArchConfig
    from vllm_distributed_tpu.models.mixtral import MixtralForCausalLM

    cfg = LlamaArchConfig(vocab_size=128, hidden_size=128,
                          intermediate_size=256, num_layers=1,
                          num_q_heads=4, num_kv_heads=2, head_dim=32,
                          num_experts=8, num_experts_per_tok=2)
    model = MixtralForCausalLM(cfg)
    rng = np.random.default_rng(0)
    E, H, I = 8, 128, 256
    lp = {
        "router": jnp.asarray(rng.standard_normal((H, E)), jnp.float32),
        "w_gate": jnp.asarray(rng.standard_normal((E, H, I)), jnp.float32),
        "w_up": jnp.asarray(rng.standard_normal((E, H, I)), jnp.float32),
        "w_down": jnp.asarray(rng.standard_normal((E, I, H)), jnp.float32),
    }
    x = jnp.asarray(rng.standard_normal((64, H)), jnp.float32)

    # Dense-path cost from XLA's own analysis; ragged-path cost counted
    # from the grouped-GEMM primitives in the jaxpr (a ragged_dot
    # computes 2*m*k*n FLOPs over its m total rows on TPU — the CPU
    # test backend expands it with masks, so its cost_analysis cannot
    # see the saving).
    monkeypatch.setenv("VDT_MOE_BACKEND", "dense")
    assert envs.VDT_MOE_BACKEND == "dense"
    dense_cost = (jax.jit(lambda x: model.mlp_block(lp, x))
                  .lower(x).compile().cost_analysis())
    dense = float(dense_cost["flops"])
    y_dense = jax.jit(lambda x: model.mlp_block(lp, x))(x)

    monkeypatch.setenv("VDT_MOE_BACKEND", "ragged")
    jaxpr = jax.make_jaxpr(lambda x: model.mlp_block(lp, x))(x)
    ragged_eqns = [e for e in jaxpr.jaxpr.eqns
                   if "ragged_dot" in str(e.primitive)]
    assert len(ragged_eqns) == 3  # gate, up, down grouped GEMMs
    ragged = 0.0
    for e in ragged_eqns:
        (m, kdim) = e.invars[0].aval.shape
        n = e.invars[1].aval.shape[-1]
        ragged += 2.0 * m * kdim * n
    y_ragged = jax.jit(lambda x: model.mlp_block(lp, x))(x)
    np.testing.assert_allclose(np.asarray(y_dense), np.asarray(y_ragged),
                               rtol=2e-3, atol=2e-2)
    # E=8, k=2: grouped GEMMs cost 2T rows vs 8T expert-rows dense ->
    # ~4x fewer MoE FLOPs (router/overhead excluded on both sides).
    assert ragged < 0.3 * dense, (ragged, dense)
