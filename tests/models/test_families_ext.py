"""HF greedy parity for the extended families (models/families_ext.py):
every architecture on the generic block knobs — LayerNorm, partial
rotary, parallel residual, non-gated MLPs, multipliers — against tiny
random checkpoints (model: reference tests/models/ correctness suites)."""

import pytest
import torch

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

PROMPTS = [
    [3, 17, 92, 45, 8],
    [5, 9, 33, 71],
]

_COMMON = dict(vocab_size=128, hidden_size=64, num_hidden_layers=2,
               num_attention_heads=4, max_position_embeddings=64,
               eos_token_id=1)


def _llm_cases():
    from transformers import (CohereConfig, CohereForCausalLM,
                              GPTNeoXConfig, GPTNeoXForCausalLM,
                              GraniteConfig, GraniteForCausalLM,
                              NemotronConfig, NemotronForCausalLM,
                              Olmo2Config, Olmo2ForCausalLM, PhiConfig,
                              PhiForCausalLM, Qwen3MoeConfig,
                              Qwen3MoeForCausalLM, StableLmConfig,
                              StableLmForCausalLM, Starcoder2Config,
                              Starcoder2ForCausalLM)
    return {
        "granite": (GraniteForCausalLM, GraniteConfig(
            **_COMMON, intermediate_size=128, num_key_value_heads=2,
            embedding_multiplier=2.0, residual_multiplier=0.5,
            attention_multiplier=0.3, logits_scaling=1.5)),
        "qwen3moe": (Qwen3MoeForCausalLM, Qwen3MoeConfig(
            **_COMMON, intermediate_size=96, num_key_value_heads=2,
            num_experts=4, num_experts_per_tok=2,
            moe_intermediate_size=48, norm_topk_prob=True,
            head_dim=16)),
        "starcoder2": (Starcoder2ForCausalLM, Starcoder2Config(
            **_COMMON, intermediate_size=128, num_key_value_heads=2,
            use_bias=True, hidden_act="gelu_pytorch_tanh")),
        "stablelm": (StableLmForCausalLM, StableLmConfig(
            **_COMMON, intermediate_size=128, num_key_value_heads=2,
            partial_rotary_factor=0.5, use_qkv_bias=True)),
        "gptneox": (GPTNeoXForCausalLM, GPTNeoXConfig(
            **_COMMON, intermediate_size=128, rotary_pct=0.5,
            use_parallel_residual=True, hidden_act="gelu")),
        "phi": (PhiForCausalLM, PhiConfig(
            **_COMMON, intermediate_size=128, num_key_value_heads=4,
            partial_rotary_factor=0.5)),
        "cohere": (CohereForCausalLM, CohereConfig(
            **_COMMON, intermediate_size=128, num_key_value_heads=2,
            logit_scale=0.25)),
        "olmo2": (Olmo2ForCausalLM, Olmo2Config(
            **_COMMON, intermediate_size=128, num_key_value_heads=2)),
        "nemotron": (NemotronForCausalLM, NemotronConfig(
            **_COMMON, intermediate_size=128, num_key_value_heads=2,
            partial_rotary_factor=0.5)),
    }


def _hf_greedy(hf, prompt, n):
    with torch.no_grad():
        out = hf.generate(torch.tensor([prompt]), max_new_tokens=n,
                          do_sample=False, eos_token_id=None)
    return out[0].tolist()[len(prompt):]


def _run_engine(path, prompts, tag, **overrides):
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    engine = LLMEngine(EngineArgs(**args).create_engine_config())
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"{tag}-{i}", p, sp)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k].outputs[0].token_ids for k in order]


@pytest.mark.parametrize("family", sorted(_llm_cases()))
def test_family_greedy_matches_hf(family, tmp_path_factory):
    hf_cls, cfg = _llm_cases()[family]
    torch.manual_seed(0)
    hf = hf_cls(cfg).eval()
    path = str(tmp_path_factory.mktemp(f"tiny_{family}"))
    hf.save_pretrained(path, safe_serialization=True)

    got = _run_engine(path, PROMPTS, family)
    want = [_hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want, family


def test_registry_covers_25_architectures():
    from vllm_distributed_tpu.models.registry import \
        supported_architectures
    assert len(supported_architectures()) >= 25


def test_family_tp2_spot_check(tmp_path_factory):
    """One knob-heavy family (parallel residual + partial rotary +
    biases) under tensor parallelism."""
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM
    torch.manual_seed(0)
    cfg = GPTNeoXConfig(**_COMMON, intermediate_size=128, rotary_pct=0.5,
                        use_parallel_residual=True, hidden_act="gelu")
    hf = GPTNeoXForCausalLM(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_neox_tp"))
    hf.save_pretrained(path, safe_serialization=True)
    got = _run_engine(path, PROMPTS, "neoxtp", tensor_parallel_size=2)
    want = [_hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


@pytest.mark.parametrize("family", ["olmo", "olmoe", "glm"])
def test_second_wave_families_match_hf(family, tmp_path_factory):
    from transformers import (GlmConfig, GlmForCausalLM, OlmoConfig,
                              OlmoeConfig, OlmoeForCausalLM,
                              OlmoForCausalLM)
    cases = {
        "olmo": (OlmoForCausalLM, OlmoConfig(
            **_COMMON, intermediate_size=128, num_key_value_heads=2,
            clip_qkv=0.05)),
        "olmoe": (OlmoeForCausalLM, OlmoeConfig(
            **_COMMON, intermediate_size=96, num_key_value_heads=2,
            num_experts=4, num_experts_per_tok=2,
            norm_topk_prob=False)),
        "glm": (GlmForCausalLM, GlmConfig(
            **_COMMON, intermediate_size=128, num_key_value_heads=2,
            head_dim=16, partial_rotary_factor=0.5,
            attention_bias=True, pad_token_id=0)),
    }
    hf_cls, cfg = cases[family]
    torch.manual_seed(0)
    hf = hf_cls(cfg).eval()
    path = str(tmp_path_factory.mktemp(f"tiny_{family}"))
    hf.save_pretrained(path, safe_serialization=True)
    got = _run_engine(path, PROMPTS, family)
    want = [_hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want, family


def test_gemma3_mixed_rope_bases_match_hf(tmp_path_factory):
    """Gemma3: sliding layers rope with rope_local_base_freq while full
    layers use the global theta + linear scaling; sandwich norms and
    folded (1+w) qk norms."""
    from transformers import Gemma3ForCausalLM as HFG3
    from transformers import Gemma3TextConfig
    torch.manual_seed(0)
    cfg = Gemma3TextConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16,
        max_position_embeddings=64, eos_token_id=1,
        sliding_window=8, layer_types=[
            "sliding_attention", "full_attention",
            "sliding_attention", "full_attention"],
        rope_theta=1000000.0, rope_local_base_freq=10000.0,
        rope_scaling={"rope_type": "linear", "factor": 8.0},
        query_pre_attn_scalar=16)
    hf = HFG3(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_gemma3"))
    hf.save_pretrained(path, safe_serialization=True)
    got = _run_engine(path, PROMPTS, "g3")
    want = [_hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


@pytest.mark.parametrize("style", ["new", "7b"])
def test_falcon_matches_hf(style, tmp_path_factory):
    """Both Falcon generations: new decoder architecture (separate
    ln_attn/ln_mlp, grouped kv) and 7B-style (shared norm,
    multi-query)."""
    from transformers import FalconConfig
    from transformers import FalconForCausalLM as HFFalcon
    kw = dict(vocab_size=128, hidden_size=64, num_hidden_layers=2,
              num_attention_heads=4, eos_token_id=1,
              parallel_attn=True, bias=False, alibi=False)
    if style == "new":
        cfg = FalconConfig(**kw, new_decoder_architecture=True,
                           num_kv_heads=2)
    else:
        cfg = FalconConfig(**kw, new_decoder_architecture=False,
                           multi_query=True)
    torch.manual_seed(0)
    hf = HFFalcon(cfg).eval()
    path = str(tmp_path_factory.mktemp(f"tiny_falcon_{style}"))
    hf.save_pretrained(path, safe_serialization=True)
    got = _run_engine(path, PROMPTS, f"falc{style}")
    want = [_hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_falcon2_single_ln_new_arch(tmp_path_factory):
    """Falcon2-11B shape: new_decoder_architecture with ONE shared norm
    (num_ln_in_parallel_attn=1)."""
    from transformers import FalconConfig
    from transformers import FalconForCausalLM as HFFalcon
    cfg = FalconConfig(vocab_size=128, hidden_size=64,
                       num_hidden_layers=2, num_attention_heads=4,
                       eos_token_id=1, parallel_attn=True, bias=False,
                       alibi=False, new_decoder_architecture=True,
                       num_kv_heads=2, num_ln_in_parallel_attn=1)
    torch.manual_seed(0)
    hf = HFFalcon(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_falcon2"))
    hf.save_pretrained(path, safe_serialization=True)
    got = _run_engine(path, PROMPTS, "falc2")
    want = [_hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_persimmon_matches_hf(tmp_path_factory):
    """Persimmon: per-head qk LayerNorms with biases + relu^2 MLP +
    partial rotary + interleaved fused QKV."""
    from transformers import PersimmonConfig
    from transformers import PersimmonForCausalLM as HFPersimmon
    cfg = PersimmonConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, eos_token_id=1,
        partial_rotary_factor=0.5, qk_layernorm=True)
    torch.manual_seed(0)
    hf = HFPersimmon(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_persimmon"))
    hf.save_pretrained(path, safe_serialization=True)
    got = _run_engine(path, PROMPTS, "persimmon")
    want = [_hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_granitemoe_matches_hf(tmp_path_factory):
    """GraniteMoe: fused [gate; up] expert tensors + the four Granite
    multipliers (reference: models/granitemoe.py)."""
    import transformers

    from tests.models._engine_harness import hf_greedy, run_engine

    cfg = transformers.GraniteMoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=32,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, num_local_experts=4,
        num_experts_per_tok=2, max_position_embeddings=64,
        embedding_multiplier=2.0, attention_multiplier=0.2,
        residual_multiplier=0.8, logits_scaling=1.5, eos_token_id=1)
    torch.manual_seed(11)
    hf = transformers.GraniteMoeForCausalLM(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_granitemoe"))
    hf.save_pretrained(path, safe_serialization=True)
    got = run_engine(path, PROMPTS, max_tokens=6)
    for p, toks in zip(PROMPTS, got):
        assert toks == hf_greedy(hf, p, 6), f"prompt {p}"


def test_dbrx_matches_hf(tmp_path_factory):
    """DBRX: flat stacked expert tensors, fused clipped Wqkv, bias-free
    LayerNorms (reference: models/dbrx.py)."""
    import transformers

    from tests.models._engine_harness import hf_greedy, run_engine

    cfg = transformers.DbrxConfig(
        d_model=64, n_heads=4, n_layers=2, max_seq_len=64,
        vocab_size=128,
        attn_config=dict(kv_n_heads=2, clip_qkv=8.0,
                         rope_theta=10000.0),
        ffn_config=dict(ffn_hidden_size=32, moe_num_experts=4,
                        moe_top_k=2), eos_token_id=1)
    torch.manual_seed(12)
    hf = transformers.DbrxForCausalLM(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_dbrx"))
    hf.save_pretrained(path, safe_serialization=True)
    got = run_engine(path, PROMPTS, max_tokens=6)
    for p, toks in zip(PROMPTS, got):
        assert toks == hf_greedy(hf, p, 6), f"prompt {p}"


def test_gpt_oss_matches_hf(tmp_path_factory):
    """gpt-oss: attention sinks, alternating sliding/full layers,
    biased router + clamped-GLU experts with interleaved gate_up
    tensors (reference: models/gpt_oss.py)."""
    import transformers

    from tests.models._engine_harness import hf_greedy, run_engine

    cfg = transformers.GptOssConfig(
        vocab_size=128, hidden_size=64, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, num_local_experts=4,
        num_experts_per_tok=2, sliding_window=8,
        max_position_embeddings=64, head_dim=16, eos_token_id=1)
    torch.manual_seed(13)
    hf = transformers.GptOssForCausalLM(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_gptoss"))
    hf.save_pretrained(path, safe_serialization=True)
    got = run_engine(path, PROMPTS, max_tokens=6)
    for p, toks in zip(PROMPTS, got):
        assert toks == hf_greedy(hf, p, 6), f"prompt {p}"


def test_phimoe_sparsemixer_matches_hf(tmp_path_factory):
    """Phi-3.5-MoE: sparsemixer routing (argmax over jitter-thresholded
    scores, softmax over survivors) must match HF exactly at
    inference (reference: models/phimoe.py)."""
    import transformers

    from tests.models._engine_harness import hf_greedy, run_engine

    cfg = transformers.PhimoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, num_local_experts=4,
        num_experts_per_tok=2, max_position_embeddings=64,
        attention_bias=True, eos_token_id=1)
    torch.manual_seed(14)
    hf = transformers.PhimoeForCausalLM(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_phimoe"))
    hf.save_pretrained(path, safe_serialization=True)
    got = run_engine(path, PROMPTS, max_tokens=6)
    for p, toks in zip(PROMPTS, got):
        assert toks == hf_greedy(hf, p, 6), f"prompt {p}"


def test_glm4_sandwich_norms_match_hf(tmp_path_factory):
    """GLM-4-0414: GLM block + sandwich norms on sub-block outputs
    (reference: models/glm4.py)."""
    import transformers

    from tests.models._engine_harness import hf_greedy, run_engine

    cfg = transformers.Glm4Config(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        head_dim=16, pad_token_id=0, eos_token_id=1)
    torch.manual_seed(15)
    hf = transformers.Glm4ForCausalLM(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_glm4"))
    hf.save_pretrained(path, safe_serialization=True)
    got = run_engine(path, PROMPTS, max_tokens=6)
    for p, toks in zip(PROMPTS, got):
        assert toks == hf_greedy(hf, p, 6), f"prompt {p}"


def test_olmo3_windows_match_hf(tmp_path_factory):
    """OLMo-3: the OLMo-2 post-norm block with per-layer sliding
    windows (reference: models/olmo3.py)."""
    import transformers

    from tests.models._engine_harness import hf_greedy, run_engine

    cfg = transformers.Olmo3Config(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=4, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=256,
        sliding_window=8, layer_types=["sliding_attention",
                                       "full_attention"] * 2,
        rope_scaling={"rope_type": "yarn", "factor": 4.0,
                      "original_max_position_embeddings": 64},
        eos_token_id=1)
    torch.manual_seed(16)
    hf = transformers.Olmo3ForCausalLM(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_olmo3"))
    hf.save_pretrained(path, safe_serialization=True)
    got = run_engine(path, PROMPTS, max_tokens=8)
    for p, toks in zip(PROMPTS, got):
        assert toks == hf_greedy(hf, p, 8), f"prompt {p}"


@pytest.mark.parametrize("family", ["ministral", "vaultgemma",
                                    "smollm3", "cohere2", "exaone4"])
def test_round5_families_match_hf(family, tmp_path_factory):
    """Round-5 additions: uniform-sliding Ministral, Gemma2-knob
    VaultGemma, and the NoPE layouts (SmolLM3 every-4th-layer NoPE;
    Cohere2/EXAONE-4 hybrids whose full-attention layers skip
    rotary)."""
    from transformers import (Cohere2Config, Cohere2ForCausalLM,
                              Exaone4Config, Exaone4ForCausalLM,
                              MinistralConfig, MinistralForCausalLM,
                              SmolLM3Config, SmolLM3ForCausalLM,
                              VaultGemmaConfig, VaultGemmaForCausalLM)
    cases = {
        "ministral": (MinistralForCausalLM, MinistralConfig(
            **_COMMON, intermediate_size=128, num_key_value_heads=2,
            head_dim=16, sliding_window=8,
            layer_types=["sliding_attention"] * 2)),
        "vaultgemma": (VaultGemmaForCausalLM, VaultGemmaConfig(
            **_COMMON, intermediate_size=128, num_key_value_heads=2,
            head_dim=16, sliding_window=8, query_pre_attn_scalar=16,
            final_logit_softcapping=30.0,
            layer_types=["sliding_attention", "full_attention"])),
        "smollm3": (SmolLM3ForCausalLM, SmolLM3Config(
            **_COMMON, intermediate_size=128, num_key_value_heads=2,
            head_dim=16, pad_token_id=0, no_rope_layers=[1, 0],
            no_rope_layer_interval=2)),
        "cohere2": (Cohere2ForCausalLM, Cohere2Config(
            **_COMMON, intermediate_size=128, num_key_value_heads=2,
            logit_scale=0.25, sliding_window=8,
            layer_types=["sliding_attention", "full_attention"],
            sliding_window_pattern=2)),
        "exaone4": (Exaone4ForCausalLM, Exaone4Config(
            **_COMMON, intermediate_size=128, num_key_value_heads=2,
            head_dim=16, sliding_window=8,
            layer_types=["sliding_attention", "full_attention"])),
    }
    hf_cls, cfg = cases[family]
    torch.manual_seed(0)
    hf = hf_cls(cfg).eval()
    path = str(tmp_path_factory.mktemp(f"tiny_{family}"))
    hf.save_pretrained(path, safe_serialization=True)
    got = _run_engine(path, PROMPTS, family)
    want = [_hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want, family


@pytest.mark.parametrize("family", ["hunyuan", "flexolmo",
                                    "granitemoeshared"])
def test_round5_moe_families_match_hf(family, tmp_path_factory):
    """Second round-5 wave: per-head-qk-norm HunYuan, post-norm MoE
    FlexOlmo, and GraniteMoe + ungated shared MLP."""
    from transformers import (FlexOlmoConfig, FlexOlmoForCausalLM,
                              GraniteMoeSharedConfig,
                              GraniteMoeSharedForCausalLM,
                              HunYuanDenseV1Config,
                              HunYuanDenseV1ForCausalLM)
    cases = {
        "hunyuan": (HunYuanDenseV1ForCausalLM, HunYuanDenseV1Config(
            **_COMMON, intermediate_size=128, num_key_value_heads=2,
            head_dim=16, pad_token_id=0)),
        "flexolmo": (FlexOlmoForCausalLM, FlexOlmoConfig(
            **_COMMON, intermediate_size=96, num_key_value_heads=2,
            num_experts=4, num_experts_per_tok=2, pad_token_id=0)),
        "granitemoeshared": (GraniteMoeSharedForCausalLM,
                             GraniteMoeSharedConfig(
            **_COMMON, intermediate_size=96, num_key_value_heads=2,
            num_local_experts=4, num_experts_per_tok=2,
            shared_intermediate_size=64, pad_token_id=0)),
    }
    hf_cls, cfg = cases[family]
    torch.manual_seed(0)
    hf = hf_cls(cfg).eval()
    path = str(tmp_path_factory.mktemp(f"tiny_{family}"))
    hf.save_pretrained(path, safe_serialization=True)
    got = _run_engine(path, PROMPTS, family)
    want = [_hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want, family


def test_ernie45_moe_dense_prefix_matches_hf(tmp_path_factory):
    """ERNIE-4.5 MoE: layer 0 dense, routed layers with
    bias-for-selection softmax routing + ungated shared experts
    (models/moe_mixed.py dense-prefix machinery)."""
    from transformers import Ernie4_5_MoeConfig, Ernie4_5_MoeForCausalLM
    cfg = Ernie4_5_MoeConfig(
        **_COMMON, intermediate_size=128, num_key_value_heads=2,
        moe_num_experts=4, moe_k=2, moe_intermediate_size=48,
        moe_num_shared_experts=1, moe_layer_start_index=1,
        pad_token_id=0)
    torch.manual_seed(0)
    hf = Ernie4_5_MoeForCausalLM(cfg).eval()
    # Non-zero correction bias so the selection-vs-weighting split is
    # actually exercised.
    with torch.no_grad():
        for layer in hf.model.layers[1:]:
            layer.mlp.moe_statics.e_score_correction_bias.copy_(
                torch.randn(1, 4) * 0.5)
    path = str(tmp_path_factory.mktemp("tiny_ernie45moe"))
    hf.save_pretrained(path, safe_serialization=True)
    got = _run_engine(path, PROMPTS, "ernie45moe")
    want = [_hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_glm4_moe_dense_prefix_matches_hf(tmp_path_factory):
    """GLM-4-MoE: first_k_dense_replace dense prefix + V3-style
    sigmoid/bias routing + shared experts + partial rotary + per-head
    qk norm."""
    from transformers import Glm4MoeConfig, Glm4MoeForCausalLM
    cfg = Glm4MoeConfig(
        **_COMMON, intermediate_size=128, num_key_value_heads=2,
        n_routed_experts=4, num_experts_per_tok=2,
        moe_intermediate_size=48, n_shared_experts=1,
        first_k_dense_replace=1, head_dim=16, use_qk_norm=True,
        partial_rotary_factor=0.5, routed_scaling_factor=1.5,
        norm_topk_prob=True, n_group=2, topk_group=2, pad_token_id=0)
    torch.manual_seed(0)
    hf = Glm4MoeForCausalLM(cfg).eval()
    with torch.no_grad():
        hf.model.layers[1].mlp.gate.e_score_correction_bias.copy_(
            torch.randn(4) * 0.5)
    path = str(tmp_path_factory.mktemp("tiny_glm4moe"))
    hf.save_pretrained(path, safe_serialization=True)
    got = _run_engine(path, PROMPTS, "glm4moe")
    want = [_hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_dots1_dense_prefix_matches_hf(tmp_path_factory):
    """dots.llm1: GLM-4-MoE recipe + always-on per-head qk norm +
    sliding layer_types."""
    from transformers import Dots1Config, Dots1ForCausalLM
    cfg = Dots1Config(
        **_COMMON, intermediate_size=128, num_key_value_heads=2,
        n_routed_experts=4, num_experts_per_tok=2,
        moe_intermediate_size=48, n_shared_experts=1,
        first_k_dense_replace=1, routed_scaling_factor=1.5,
        n_group=2, topk_group=2, norm_topk_prob=True,
        sliding_window=8,
        layer_types=["sliding_attention", "full_attention"],
        pad_token_id=0)
    torch.manual_seed(0)
    hf = Dots1ForCausalLM(cfg).eval()
    with torch.no_grad():
        hf.model.layers[1].mlp.gate.e_score_correction_bias.copy_(
            torch.randn(4) * 0.5)
    path = str(tmp_path_factory.mktemp("tiny_dots1"))
    hf.save_pretrained(path, safe_serialization=True)
    got = _run_engine(path, PROMPTS, "dots1")
    want = [_hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want
