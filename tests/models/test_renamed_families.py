"""InternLM2 / Baichuan: Llama math behind renamed + fused checkpoint
tensors (reference: models/internlm2.py split_qkv, models/baichuan.py
W_pack). transformers ships neither class (both are trust_remote_code
upstream), so parity is proven by EQUIVALENCE: rewrite a Llama
checkpoint into each format and require byte-identical engine outputs
on the same underlying weights."""

import json
import os

import numpy as np
import pytest
import torch
from safetensors.numpy import save_file
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

CFG = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=2, max_position_embeddings=64,
           eos_token_id=1)
HEAD_DIM = 16


@pytest.fixture(scope="module")
def llama_ckpt(tmp_path_factory):
    torch.manual_seed(0)
    hf = HFLlama(LlamaConfig(**CFG))
    path = tmp_path_factory.mktemp("tiny_llama_base")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def _state(path):
    from safetensors import safe_open
    import glob
    out = {}
    for f in glob.glob(os.path.join(path, "*.safetensors")):
        with safe_open(f, framework="np") as r:
            for k in r.keys():
                out[k] = r.get_tensor(k)
    return out


def _save_variant(tmp_path_factory, name, arch, tensors):
    path = str(tmp_path_factory.mktemp(name))
    save_file(tensors, os.path.join(path, "model.safetensors"))
    cfg = dict(CFG, architectures=[arch], model_type="llama")
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(cfg, f)
    return path


def run(path, prompts):
    engine = LLMEngine(EngineArgs(
        model=path, dtype="float32", block_size=4,
        num_gpu_blocks_override=64, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
        skip_tokenizer_init=True).create_engine_config())
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"r-{i}", p, sp)
    done = {}
    for _ in range(200):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out.outputs[0].token_ids
        if not engine.has_unfinished_requests():
            break
    return [done[f"r-{i}"] for i in range(len(prompts))]


PROMPTS = [[3, 17, 92, 45, 8], [5, 9, 33, 71]]


def test_internlm2_grouped_wqkv_equivalence(llama_ckpt, tmp_path_factory):
    sd = _state(llama_ckpt)
    kv, q_per_kv, hd = 2, 2, HEAD_DIM
    H = CFG["hidden_size"]
    out = {"model.tok_embeddings.weight": sd["model.embed_tokens.weight"],
           "model.norm.weight": sd["model.norm.weight"],
           "output.weight": sd["lm_head.weight"]}
    for i in range(CFG["num_hidden_layers"]):
        pre = f"model.layers.{i}."
        q = sd[f"{pre}self_attn.q_proj.weight"].reshape(kv, q_per_kv, hd, H)
        k = sd[f"{pre}self_attn.k_proj.weight"].reshape(kv, 1, hd, H)
        v = sd[f"{pre}self_attn.v_proj.weight"].reshape(kv, 1, hd, H)
        out[f"{pre}attention.wqkv.weight"] = np.concatenate(
            [q, k, v], axis=1).reshape(-1, H)
        out[f"{pre}attention.wo.weight"] = \
            sd[f"{pre}self_attn.o_proj.weight"]
        out[f"{pre}feed_forward.w1.weight"] = \
            sd[f"{pre}mlp.gate_proj.weight"]
        out[f"{pre}feed_forward.w3.weight"] = sd[f"{pre}mlp.up_proj.weight"]
        out[f"{pre}feed_forward.w2.weight"] = \
            sd[f"{pre}mlp.down_proj.weight"]
        out[f"{pre}attention_norm.weight"] = \
            sd[f"{pre}input_layernorm.weight"]
        out[f"{pre}ffn_norm.weight"] = \
            sd[f"{pre}post_attention_layernorm.weight"]
    path = _save_variant(tmp_path_factory, "tiny_internlm2",
                         "InternLM2ForCausalLM", out)
    assert run(path, PROMPTS) == run(llama_ckpt, PROMPTS)


def test_baichuan_wpack_equivalence(llama_ckpt, tmp_path_factory):
    sd = _state(llama_ckpt)
    out = dict(sd)
    for i in range(CFG["num_hidden_layers"]):
        pre = f"model.layers.{i}.self_attn."
        out[f"{pre}W_pack.weight"] = np.concatenate(
            [out.pop(f"{pre}q_proj.weight"), out.pop(f"{pre}k_proj.weight"),
             out.pop(f"{pre}v_proj.weight")])
    path = _save_variant(tmp_path_factory, "tiny_baichuan",
                         "BaichuanForCausalLM", out)
    assert run(path, PROMPTS) == run(llama_ckpt, PROMPTS)


def test_baichuan_13b_selects_alibi(tmp_path_factory):
    """hidden_size >= 5120 flips the family to ALiBi + no rope (the
    reference keys position_embedding on the 13B name,
    baichuan.py:330); the arch knobs must reflect it."""
    from types import SimpleNamespace

    from vllm_distributed_tpu.models.families import BaichuanForCausalLM
    from vllm_distributed_tpu.models.llama import LlamaArchConfig
    hf = SimpleNamespace(vocab_size=64, hidden_size=5120,
                         intermediate_size=128, num_hidden_layers=2,
                         num_attention_heads=40, num_key_value_heads=40,
                         head_dim=128, rms_norm_eps=1e-6,
                         tie_word_embeddings=False)
    arch = LlamaArchConfig.from_hf_config(
        BaichuanForCausalLM.arch_config_source(hf))
    BaichuanForCausalLM.configure_arch(arch, hf)
    assert arch.alibi and arch.pos_embedding == "none"
    hf.hidden_size = 4096  # 7B stays rope
    arch7 = LlamaArchConfig.from_hf_config(
        BaichuanForCausalLM.arch_config_source(hf))
    BaichuanForCausalLM.configure_arch(arch7, hf)
    assert not arch7.alibi and arch7.pos_embedding == "rope"
