"""Sequence parallelism (reference: the enable_sequence_parallelism
compile pass, compilation/sequence_parallelism.py): token-sharding the
residual stream over the TP axis must not change results — GSPMD
rewrites the collectives, not the math."""

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg)
    path = tmp_path_factory.mktemp("tiny_llama_sp")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def run(path, **overrides):
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    engine = LLMEngine(EngineArgs(**args).create_engine_config())
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True,
                        logprobs=3)
    prompts = [[3, 17, 92, 45, 8, 21, 33], [5, 9, 33, 71]]
    for i, p in enumerate(prompts):
        engine.add_request(f"r-{i}", p, sp)
    done = {}
    for _ in range(200):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert len(done) == len(prompts)
    return done


def test_sp_matches_plain_tp(checkpoint):
    base = run(checkpoint, tensor_parallel_size=2)
    spar = run(checkpoint, tensor_parallel_size=2,
               enable_sequence_parallel=True)
    for rid in base:
        assert (base[rid].outputs[0].token_ids
                == spar[rid].outputs[0].token_ids), rid
        for lp_b, lp_s in zip(base[rid].outputs[0].logprobs,
                              spar[rid].outputs[0].logprobs):
            common = set(lp_b) & set(lp_s)
            assert common
            for tok in common:
                assert abs(lp_b[tok] - lp_s[tok]) < 1e-3


def test_sp_composes_with_quant_and_gqa(checkpoint):
    base = run(checkpoint, tensor_parallel_size=4, quantization="int8")
    spar = run(checkpoint, tensor_parallel_size=4, quantization="int8",
               enable_sequence_parallel=True)
    for rid in base:
        assert (base[rid].outputs[0].token_ids
                == spar[rid].outputs[0].token_ids), rid
