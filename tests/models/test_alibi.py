"""ALiBi families (Bloom/MPT) and the slope-bias attention path
(reference: models/bloom.py, mpt.py and the alibi_slopes arg threaded
through the reference attention backends)."""

import numpy as np
import torch
import transformers

from tests.models._engine_harness import PROMPTS, hf_greedy, run_engine
from vllm_distributed_tpu.models.common import alibi_slopes


def test_alibi_slopes_match_published_recipe():
    # Power-of-two head counts: geometric 2^(-8i/n).
    np.testing.assert_allclose(
        alibi_slopes(8), [2.0 ** (-(i + 1)) for i in range(8)])
    # Non-power-of-two (e.g. 12 heads): 8-head ladder + every other
    # entry of the 16-head ladder.
    s12 = alibi_slopes(12)
    assert len(s12) == 12
    np.testing.assert_allclose(s12[:8], alibi_slopes(8))
    np.testing.assert_allclose(s12[8:], alibi_slopes(16)[0::2][:4])


def _save(tmp_path_factory, name, hf):
    path = str(tmp_path_factory.mktemp(name))
    hf.save_pretrained(path, safe_serialization=True)
    return path, hf


def _check(path, hf, n=6, **overrides):
    got = run_engine(path, PROMPTS, max_tokens=n, **overrides)
    for p, toks in zip(PROMPTS, got):
        assert toks == hf_greedy(hf, p, n), f"prompt {p}"


def test_bloom_matches_hf(tmp_path_factory):
    cfg = transformers.BloomConfig(
        vocab_size=128, hidden_size=64, n_layer=2, n_head=4,
        eos_token_id=1)
    torch.manual_seed(0)
    path, hf = _save(tmp_path_factory, "tiny_bloom",
                     transformers.BloomForCausalLM(cfg).eval())
    _check(path, hf)


def test_mpt_matches_hf(tmp_path_factory):
    cfg = transformers.MptConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4,
        expansion_ratio=2, no_bias=True,
        attn_config={"alibi": True, "qk_ln": False},
        eos_token_id=1)
    torch.manual_seed(1)
    path, hf = _save(tmp_path_factory, "tiny_mpt",
                     transformers.MptForCausalLM(cfg).eval())
    _check(path, hf)


def test_bloom_matches_hf_under_tp2(tmp_path_factory):
    """The XLA alibi path under GSPMD TP: per-head slopes must follow
    their heads across the model-axis shards."""
    cfg = transformers.BloomConfig(
        vocab_size=128, hidden_size=64, n_layer=2, n_head=4,
        eos_token_id=1)
    torch.manual_seed(2)
    path, hf = _save(tmp_path_factory, "tiny_bloom_tp",
                     transformers.BloomForCausalLM(cfg).eval())
    _check(path, hf, tensor_parallel_size=2)
