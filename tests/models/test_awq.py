"""AWQ checkpoint loading (reference: quantization/awq.py runtime
kernels -> here host-side dequantize-on-load): pack/unpack roundtrip
against the documented AutoAWQ gemm layout, and engine equivalence
between a packed AWQ checkpoint and the same weights stored plain."""

import json
import os

import numpy as np
import pytest
import torch
from safetensors.numpy import save_file
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.models.gptq import dequantize_awq_layer
from vllm_distributed_tpu.sampling_params import SamplingParams

GROUP = 16
_ORDER = [0, 2, 4, 6, 1, 3, 5, 7]  # AutoAWQ pack_intweight order_map


def _awq_pack(vals: np.ndarray) -> np.ndarray:
    """AutoAWQ gemm packing: 8 int4 values per int32 word along the
    OUTPUT dim; bit-slot i holds real column col*8 + order_map[i]."""
    in_dim, out_dim = vals.shape
    words = np.zeros((in_dim, out_dim // 8), np.uint32)
    for i, off in enumerate(_ORDER):
        words |= vals[:, off::8].astype(np.uint32) << (i * 4)
    return np.ascontiguousarray(words.astype(np.int32))


def quantize_awq(w: np.ndarray, group=GROUP):
    """Groupwise-quantize a torch-orientation [out, in] matrix into the
    AutoAWQ gemm tensor set (asymmetric, zero stored as-is)."""
    out_dim, in_dim = w.shape
    wg = w.T.reshape(in_dim // group, group, out_dim)  # [G, g, out]
    wmin, wmax = wg.min(axis=1), wg.max(axis=1)
    scales = np.maximum((wmax - wmin) / 15.0, 1e-8)
    zeros = np.clip(np.round(-wmin / scales), 0, 15)
    q = np.clip(np.round(wg / scales[:, None]) + zeros[:, None], 0,
                15).astype(np.uint32).reshape(in_dim, out_dim)
    # The checkpoint stores fp16 scales; compute the expected dequant
    # with the SAME rounding so engine comparisons are exact.
    s16 = scales.astype(np.float16).astype(np.float32)
    g_idx = np.arange(in_dim) // group
    return {
        "qweight": _awq_pack(q),
        "qzeros": _awq_pack(zeros.astype(np.uint32)),
        # C-contiguous: safetensors serializes the raw buffer assuming
        # C order (an F-ordered view would scramble silently).
        "scales": np.ascontiguousarray(scales.astype(np.float16)),
    }, np.ascontiguousarray(
        (s16[g_idx] * (q.astype(np.float32) - zeros[g_idx])).T)


def test_awq_pack_dequant_roundtrip():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((32, 64)).astype(np.float32)  # [out, in]
    packed, expect = quantize_awq(w)
    got = dequantize_awq_layer(packed["qweight"], packed["qzeros"],
                               packed["scales"], GROUP)
    np.testing.assert_allclose(got, expect, rtol=1e-3, atol=1e-3)
    # And the dequantized matrix approximates the original.
    assert np.abs(got - w).max() < 0.2


def test_awq_checkpoint_matches_plain_engine(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64, eos_token_id=1)
    hf = HFLlama(cfg).eval()
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}

    packed_sd, plain_sd = {}, {}
    quant_suffixes = ("q_proj.weight", "k_proj.weight", "v_proj.weight",
                      "o_proj.weight", "gate_proj.weight",
                      "up_proj.weight", "down_proj.weight")
    for name, w in sd.items():
        if name.endswith(quant_suffixes):
            packed, deq = quantize_awq(np.asarray(w, np.float32))
            base = name.rsplit(".weight", 1)[0]
            for suffix, t in packed.items():
                packed_sd[f"{base}.{suffix}"] = t
            plain_sd[name] = deq.astype(np.float32)
        else:
            packed_sd[name] = np.asarray(w)
            plain_sd[name] = np.asarray(w)

    paths = {}
    for tag, tensors, qconf in (
            ("awq", packed_sd, {"quant_method": "awq", "bits": 4,
                                "group_size": GROUP, "version": "gemm",
                                "zero_point": True}),
            ("plain", plain_sd, None)):
        path = tmp_path_factory.mktemp(f"tiny_{tag}")
        save_file(tensors, os.path.join(path, "model.safetensors"))
        conf = json.loads(cfg.to_json_string())
        conf["architectures"] = ["LlamaForCausalLM"]
        if qconf:
            conf["quantization_config"] = qconf
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(conf, f)
        paths[tag] = str(path)

    def run(path):
        engine = LLMEngine(EngineArgs(
            model=path, dtype="float32", block_size=4,
            num_gpu_blocks_override=128, max_model_len=64,
            max_num_batched_tokens=64, max_num_seqs=8,
            skip_tokenizer_init=True).create_engine_config())
        sp = SamplingParams(temperature=0.0, max_tokens=6,
                            ignore_eos=True)
        engine.add_request("q-0", [3, 17, 92, 45, 8], sp)
        for _ in range(100):
            for out in engine.step():
                if out.finished:
                    return out.outputs[0].token_ids
        raise AssertionError("did not finish")

    assert run(paths["awq"]) == run(paths["plain"])
