"""HF greedy parity for the non-Llama model families (Gemma, Qwen3,
Phi-3) — same harness as tests/models/test_llama.py (reference pattern:
tests/models/ per-arch correctness vs HfRunner)."""

import pytest
import torch
from transformers import (GemmaConfig, GemmaForCausalLM, Phi3Config,
                          Phi3ForCausalLM, Qwen3Config, Qwen3ForCausalLM)

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

PROMPTS = [
    [3, 17, 92, 45, 8],
    [5, 9, 33, 71],
]


def _save(tmp_path_factory, name, hf):
    path = tmp_path_factory.mktemp(name)
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf.eval()


def hf_greedy(hf, prompt, n):
    with torch.no_grad():
        out = hf.generate(torch.tensor([prompt]), max_new_tokens=n,
                          do_sample=False, eos_token_id=None)
    return out[0].tolist()[len(prompt):]


def run(path, prompts, **overrides):
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    engine = LLMEngine(EngineArgs(**args).create_engine_config())
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"r-{i}", p, sp)
    done = {}
    for _ in range(200):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    return [done[f"r-{i}"].outputs[0].token_ids
            for i in range(len(prompts))]


def test_gemma_greedy_matches_hf(tmp_path_factory):
    torch.manual_seed(0)
    cfg = GemmaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      head_dim=16, max_position_embeddings=64,
                      eos_token_id=1)
    path, hf = _save(tmp_path_factory, "tiny_gemma",
                     GemmaForCausalLM(cfg))
    got = run(path, PROMPTS)
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_qwen3_greedy_matches_hf(tmp_path_factory):
    torch.manual_seed(0)
    cfg = Qwen3Config(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      head_dim=16, max_position_embeddings=64,
                      eos_token_id=1)
    path, hf = _save(tmp_path_factory, "tiny_qwen3",
                     Qwen3ForCausalLM(cfg))
    got = run(path, PROMPTS)
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_qwen3_tp2_matches_hf(tmp_path_factory):
    torch.manual_seed(1)
    cfg = Qwen3Config(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      head_dim=16, max_position_embeddings=64,
                      eos_token_id=1)
    path, hf = _save(tmp_path_factory, "tiny_qwen3_tp",
                     Qwen3ForCausalLM(cfg))
    got = run(path, PROMPTS, tensor_parallel_size=2)
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_phi3_greedy_matches_hf(tmp_path_factory):
    torch.manual_seed(0)
    cfg = Phi3Config(vocab_size=128, hidden_size=64,
                     intermediate_size=128, num_hidden_layers=2,
                     num_attention_heads=4, num_key_value_heads=2,
                     max_position_embeddings=64, eos_token_id=1,
                     pad_token_id=0)
    path, hf = _save(tmp_path_factory, "tiny_phi3",
                     Phi3ForCausalLM(cfg))
    got = run(path, PROMPTS)
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_mistral_sliding_window_matches_hf(tmp_path_factory):
    """Sliding-window attention (window smaller than the prompt) must
    match HF MistralForCausalLM exactly."""
    from transformers import MistralConfig, MistralForCausalLM
    torch.manual_seed(0)
    cfg = MistralConfig(vocab_size=128, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        sliding_window=8, max_position_embeddings=64,
                        eos_token_id=1, attn_implementation="eager")
    path, hf = _save(tmp_path_factory, "tiny_mistral_sw",
                     MistralForCausalLM(cfg))
    long_prompt = [3, 17, 92, 45, 8, 21, 33, 64, 90, 11, 12, 13]  # > W
    got = run(path, [long_prompt], max_model_len=32)
    want = [hf_greedy(hf, long_prompt, 6)]
    assert got == want


def test_gemma2_greedy_matches_hf(tmp_path_factory):
    """Gemma2: sandwich norms, logit soft-capping, query_pre_attn_scalar
    scaling, and alternating sliding/full layers (hf.layer_types) must
    match HF Gemma2ForCausalLM (eager — sdpa drops the softcap)."""
    from transformers import Gemma2Config
    from transformers import Gemma2ForCausalLM as HFGemma2
    torch.manual_seed(0)
    cfg = Gemma2Config(vocab_size=128, hidden_size=64,
                       intermediate_size=128, num_hidden_layers=4,
                       num_attention_heads=4, num_key_value_heads=2,
                       head_dim=16, sliding_window=4,
                       max_position_embeddings=64, eos_token_id=1,
                       attn_implementation="eager")
    path, hf = _save(tmp_path_factory, "tiny_gemma2", HFGemma2(cfg))
    long_prompt = [3, 17, 92, 45, 8, 21, 33, 64, 90, 11, 12, 13]  # > W
    got = run(path, [long_prompt, PROMPTS[1]])
    want = [hf_greedy(hf, p, 6) for p in [long_prompt, PROMPTS[1]]]
    assert got == want


def test_gemma2_pp2_matches_hf(tmp_path_factory):
    """PP=2 over the alternating window pattern: each stage's jit must
    pick up its own slice of the layout (first_layer offsets)."""
    from transformers import Gemma2Config
    from transformers import Gemma2ForCausalLM as HFGemma2
    torch.manual_seed(1)
    cfg = Gemma2Config(vocab_size=128, hidden_size=64,
                       intermediate_size=128, num_hidden_layers=4,
                       num_attention_heads=4, num_key_value_heads=2,
                       head_dim=16, sliding_window=4,
                       max_position_embeddings=64, eos_token_id=1,
                       attn_implementation="eager")
    path, hf = _save(tmp_path_factory, "tiny_gemma2_pp", HFGemma2(cfg))
    long_prompt = [3, 17, 92, 45, 8, 21, 33, 64, 90, 11, 12, 13]
    got = run(path, [long_prompt], pipeline_parallel_size=2)
    want = [hf_greedy(hf, long_prompt, 6)]
    assert got == want


def test_qwen2_mixed_window_layout_matches_hf(tmp_path_factory):
    """Qwen2 max_window_layers (first N layers full-causal, the rest
    windowed) runs as two scan segments and must match HF."""
    from transformers import Qwen2Config, Qwen2ForCausalLM
    torch.manual_seed(0)
    cfg = Qwen2Config(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=4,
                      num_attention_heads=4, num_key_value_heads=2,
                      sliding_window=4, use_sliding_window=True,
                      max_window_layers=2, max_position_embeddings=64,
                      eos_token_id=1, attn_implementation="eager")
    path, hf = _save(tmp_path_factory, "tiny_qwen2_mixed",
                     Qwen2ForCausalLM(cfg))
    long_prompt = [3, 17, 92, 45, 8, 21, 33, 64, 90, 11, 12, 13]
    got = run(path, [long_prompt])
    want = [hf_greedy(hf, long_prompt, 6)]
    assert got == want


def test_window_segment_planner():
    """Unit: period grouping for alternating layouts, run segmentation
    for prefix layouts, single segment for uniform ones."""
    from vllm_distributed_tpu.models.llama import LlamaForCausalLM
    plan = LlamaForCausalLM._plan_window_segments
    assert plan((0, 0, 0, 0)) == [(0, 4, (0, ))]
    assert plan((8, 8, 8)) == [(0, 3, (8, ))]
    # Gemma2 alternating: one scan over pairs.
    assert plan((4, 0, 4, 0)) == [(0, 4, (4, 0))]
    # Qwen2 prefix layout (non-periodic): two constant runs.
    assert plan((0, ) * 5 + (4, ) * 5) == [(0, 5, (0, )), (5, 5, (4, ))]
    # Odd-length slice of an alternating layout (a Gemma2 PP stage):
    # periodic bulk + one-layer remainder, NOT a per-layer unroll.
    assert plan((4, 0, 4, 0, 4)) == [(0, 4, (4, 0)), (4, 1, (4, ))]
    assert plan((4, 0) * 10 + (4, )) == [(0, 20, (4, 0)), (20, 1, (4, ))]


def test_qwen2_moe_greedy_matches_hf(tmp_path_factory):
    """Qwen2-MoE: routed experts without top-k renorm + sigmoid-gated
    shared expert + qkv bias must match HF Qwen2MoeForCausalLM."""
    from transformers import Qwen2MoeConfig
    from transformers import Qwen2MoeForCausalLM as HFQwen2Moe
    torch.manual_seed(0)
    cfg = Qwen2MoeConfig(vocab_size=128, hidden_size=64,
                         intermediate_size=128, moe_intermediate_size=32,
                         shared_expert_intermediate_size=64,
                         num_experts=4, num_experts_per_tok=2,
                         num_hidden_layers=2, num_attention_heads=4,
                         num_key_value_heads=2,
                         max_position_embeddings=64, eos_token_id=1,
                         decoder_sparse_step=1, mlp_only_layers=[])
    path, hf = _save(tmp_path_factory, "tiny_qwen2moe", HFQwen2Moe(cfg))
    got = run(path, PROMPTS)
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_qwen2_moe_ep2_matches_hf(tmp_path_factory):
    """Qwen2-MoE under expert parallelism (experts sharded over the
    model axis; the shared expert stays TP-dense)."""
    from transformers import Qwen2MoeConfig
    from transformers import Qwen2MoeForCausalLM as HFQwen2Moe
    torch.manual_seed(1)
    cfg = Qwen2MoeConfig(vocab_size=128, hidden_size=64,
                         intermediate_size=128, moe_intermediate_size=32,
                         shared_expert_intermediate_size=64,
                         num_experts=4, num_experts_per_tok=2,
                         num_hidden_layers=2, num_attention_heads=4,
                         num_key_value_heads=2,
                         max_position_embeddings=64, eos_token_id=1,
                         decoder_sparse_step=1, mlp_only_layers=[])
    path, hf = _save(tmp_path_factory, "tiny_qwen2moe_ep",
                     HFQwen2Moe(cfg))
    got = run(path, PROMPTS, tensor_parallel_size=2,
              enable_expert_parallel=True)
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_gemma2_int8_quant_keeps_top1(tmp_path_factory):
    """Gemma2 + int8 weight quantization: the extra norms/softcap path
    must compose with the dequantizing weight accessor (top-1 greedy
    token preserved on a tiny model)."""
    from transformers import Gemma2Config
    from transformers import Gemma2ForCausalLM as HFGemma2
    torch.manual_seed(2)
    cfg = Gemma2Config(vocab_size=128, hidden_size=64,
                       intermediate_size=128, num_hidden_layers=4,
                       num_attention_heads=4, num_key_value_heads=2,
                       head_dim=16, sliding_window=4,
                       max_position_embeddings=64, eos_token_id=1,
                       attn_implementation="eager")
    path, _ = _save(tmp_path_factory, "tiny_gemma2_q8", HFGemma2(cfg))
    prompt = [3, 17, 92, 45, 8, 21, 33, 64]
    fp = run(path, [prompt])
    q8 = run(path, [prompt], quantization="int8")
    # First greedy token agrees (full-sequence drift is allowed for a
    # quantized tiny model; divergence-at-step-0 would mean the scales
    # or extra-norm keys broke).
    assert fp[0][0] == q8[0][0]


def test_phi3_longrope_matches_hf(tmp_path_factory):
    """Phi-3 128k LongRoPE: per-dim long/short factors + the sqrt
    attention factor (reference: the longrope path of
    modeling_rope_utils, silently ignored before this test's feature)."""
    from transformers import Phi3Config
    from transformers import Phi3ForCausalLM as HFPhi3

    hd2 = 8  # head_dim 16 -> 8 factors
    cfg = Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=256,
        original_max_position_embeddings=64,
        rope_scaling={"type": "longrope",
                      "long_factor": [1.5 + 0.5 * i for i in range(hd2)],
                      "short_factor": [1.0 + 0.1 * i
                                       for i in range(hd2)]},
        eos_token_id=1, pad_token_id=0)
    torch.manual_seed(31)
    hf = HFPhi3(cfg).eval()
    path, hf = _save(tmp_path_factory, "tiny_phi3_longrope", hf)
    got = run(path, PROMPTS, max_model_len=128,
              max_num_batched_tokens=128)
    for p, toks in zip(PROMPTS, got):
        assert toks == hf_greedy(hf, p, 6), f"prompt {p}"


def test_qwen2_yarn_matches_hf(tmp_path_factory):
    """YaRN on the general decoder path (regression: it was silently
    ignored outside DeepSeek until the gpt-oss drive exposed it)."""
    from transformers import Qwen2Config
    from transformers import Qwen2ForCausalLM as HFQwen2

    cfg = Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=256,
        rope_scaling={"rope_type": "yarn", "factor": 4.0,
                      "original_max_position_embeddings": 64},
        eos_token_id=1)
    torch.manual_seed(32)
    hf = HFQwen2(cfg).eval()
    path, hf = _save(tmp_path_factory, "tiny_qwen2_yarn", hf)
    got = run(path, PROMPTS, max_model_len=128,
              max_num_batched_tokens=128)
    for p, toks in zip(PROMPTS, got):
        assert toks == hf_greedy(hf, p, 6), f"prompt {p}"


def test_qwen2_hardcoded_qkv_biases_load(tmp_path_factory):
    """Qwen2 hardcodes qkv biases with NO attention_bias config attr;
    the loader's auto-detection must pick them up (regression: they
    were silently dropped — zero-init biases made parity vacuous)."""
    from transformers import Qwen2Config
    from transformers import Qwen2ForCausalLM as HFQwen2

    cfg = Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        eos_token_id=1)
    torch.manual_seed(33)
    hf = HFQwen2(cfg).eval()
    with torch.no_grad():
        for name, par in hf.named_parameters():
            if name.endswith(".bias"):
                par.normal_(0.0, 0.3)  # make dropped biases visible
    path, hf = _save(tmp_path_factory, "tiny_qwen2_bias", hf)
    got = run(path, PROMPTS)
    for p, toks in zip(PROMPTS, got):
        assert toks == hf_greedy(hf, p, 6), f"prompt {p}"
