"""The expert-parallel ALL-TO-ALL dispatch mechanism (reference:
device_communicators/all2all.py + parallel_state.py:790-803): beyond
the HF-parity tests, assert the lowering actually moves rows with
all_to_all instead of psum-ing replicated activations, and that the
comm volume is per-token, not per-rank."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vllm_distributed_tpu.config import ParallelConfig
from vllm_distributed_tpu.models.llama import LlamaArchConfig
from vllm_distributed_tpu.models.mixtral import MixtralForCausalLM
from vllm_distributed_tpu.parallel.mesh import build_mesh, global_mesh

EP = 4
T, H, I, E, K = 8, 32, 16, 4, 2


@pytest.fixture()
def ep_setup():
    mesh = build_mesh(ParallelConfig(tensor_parallel_size=EP),
                      devices=jax.devices("cpu")[:EP])
    cfg = LlamaArchConfig(
        vocab_size=64, hidden_size=H, intermediate_size=I,
        num_layers=1, num_q_heads=4, num_kv_heads=4, head_dim=8,
        num_experts=E, num_experts_per_tok=K, norm_topk_prob=True,
        expert_parallel=True, expert_parallel_ranks=EP,
        dtype=jnp.float32)
    model = MixtralForCausalLM(cfg)
    rng = np.random.default_rng(0)
    lp = {
        "router": jnp.asarray(rng.normal(size=(H, E)), jnp.float32),
        "w_gate": jnp.asarray(rng.normal(size=(E, H, I)) * 0.1,
                              jnp.float32),
        "w_up": jnp.asarray(rng.normal(size=(E, H, I)) * 0.1,
                            jnp.float32),
        "w_down": jnp.asarray(rng.normal(size=(E, I, H)) * 0.1,
                              jnp.float32),
    }
    x = jnp.asarray(rng.normal(size=(T, H)), jnp.float32)
    return mesh, model, lp, x


def test_a2a_carries_the_dispatch(ep_setup, monkeypatch):
    """The jaxpr of the EP MoE block must contain all_to_all ops; the
    row-combining psum of the replicate path must be gone."""
    mesh, model, lp, x = ep_setup
    monkeypatch.setenv("VDT_MOE_EP_MODE", "a2a")
    with global_mesh(mesh), mesh:
        jaxpr = str(jax.make_jaxpr(
            lambda x_: model.mlp_block(lp, x_))(x))
    assert "all_to_all" in jaxpr
    # The replicate path's signature collective is a psum of the full
    # [T*k, H] row matrix; a2a re-replicates with a tiled all_gather
    # and needs no psum at all.
    assert "all_gather" in jaxpr
    assert "psum" not in jaxpr


def test_a2a_matches_replicate_path(ep_setup, monkeypatch):
    mesh, model, lp, x = ep_setup
    with global_mesh(mesh), mesh:
        monkeypatch.setenv("VDT_MOE_EP_MODE", "a2a")
        got = np.asarray(model.mlp_block(lp, x))
        monkeypatch.setenv("VDT_MOE_EP_MODE", "replicate")
        want = np.asarray(model.mlp_block(lp, x))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_a2a_comm_volume_is_per_token(ep_setup):
    """Worst-case bytes on the wire per direction: each rank sends its
    own T/ep * k rows (padded buckets) — summed over ranks that is
    T * k * H, independent of ep; the replicate path psums ep * T * k
    * H. This documents the scaling claim with the actual buffer
    shapes used by the implementation."""
    Tl = T // EP
    send_buffer_rows = EP * (Tl * K)       # per rank: ep buckets x cap
    total_rows_on_wire = EP * send_buffer_rows
    # Worst-case padded volume: ep * T * k rows; the USEFUL rows are
    # T * k. The replicate path moves ep * T * k useful rows through
    # its psum — a2a's padding equals replicate's useful volume only
    # at this worst case, and real routing fills ~1/ep of the buckets.
    assert total_rows_on_wire == EP * T * K
    useful = T * K
    assert useful * EP == total_rows_on_wire


def test_indivisible_bucket_falls_back(ep_setup, monkeypatch):
    """T not divisible by ep: the dispatch silently takes the exact
    replicate+psum path instead of mis-slicing."""
    mesh, model, lp, _ = ep_setup
    monkeypatch.setenv("VDT_MOE_EP_MODE", "a2a")
    rng = np.random.default_rng(1)
    x7 = jnp.asarray(rng.normal(size=(7, H)), jnp.float32)
    with global_mesh(mesh), mesh:
        assert not model._a2a_applicable(7)
        out = np.asarray(model.mlp_block(lp, x7))
    assert out.shape == (7, H)
    assert np.isfinite(out).all()


def test_a2a_composes_with_eplb(monkeypatch):
    """EPLB physical replicas under the all-to-all dispatch must match
    the replicate+psum path exactly (same global-token replica
    choice)."""
    mesh = build_mesh(ParallelConfig(tensor_parallel_size=EP),
                      devices=jax.devices("cpu")[:EP])
    cfg = LlamaArchConfig(
        vocab_size=64, hidden_size=H, intermediate_size=I,
        num_layers=1, num_q_heads=4, num_kv_heads=4, head_dim=8,
        num_experts=E, num_experts_per_tok=K, norm_topk_prob=True,
        num_physical_experts=E + EP,  # one replica slot per rank
        expert_parallel=True, expert_parallel_ranks=EP,
        dtype=jnp.float32)
    model = MixtralForCausalLM(cfg)
    rng = np.random.default_rng(3)
    Pn = model.num_physical
    lp = {
        "router": jnp.asarray(rng.normal(size=(H, E)), jnp.float32),
        "w_gate": jnp.asarray(rng.normal(size=(Pn, H, I)) * 0.1,
                              jnp.float32),
        "w_up": jnp.asarray(rng.normal(size=(Pn, H, I)) * 0.1,
                            jnp.float32),
        "w_down": jnp.asarray(rng.normal(size=(Pn, I, H)) * 0.1,
                              jnp.float32),
        # Logical e maps to itself plus one replica at slot E+e%EP...
        "expert_map": jnp.asarray(
            np.stack([np.arange(E), E + np.arange(E) % EP],
                     axis=1).astype(np.int32)),
        "expert_replicas": jnp.full((E, ), 2, jnp.int32),
    }
    # Replicas must hold the SAME weights as their logical expert for
    # output equality.
    emap = np.asarray(lp["expert_map"])
    for e in range(E):
        for w in ("w_gate", "w_up", "w_down"):
            lp[w] = lp[w].at[emap[e, 1]].set(lp[w][e])
    x = jnp.asarray(rng.normal(size=(T, H)), jnp.float32)
    with global_mesh(mesh), mesh:
        monkeypatch.setenv("VDT_MOE_EP_MODE", "a2a")
        got = np.asarray(model.mlp_block(lp, x))
        monkeypatch.setenv("VDT_MOE_EP_MODE", "replicate")
        want = np.asarray(model.mlp_block(lp, x))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
