"""Qwen2-VL: dynamic-resolution vision tower, M-RoPE decoder, video
inputs (reference: vllm/model_executor/models/qwen2_vl.py + its HF
parity tests)."""

import numpy as np
import pytest
import torch
from transformers import Qwen2VLConfig, Qwen2VLForConditionalGeneration

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

IMG_TOK, VID_TOK, VSTART, VEND = 151, 152, 153, 154


def tiny_cfg():
    return Qwen2VLConfig(
        text_config=dict(
            vocab_size=160, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=512,
            rope_scaling={"type": "mrope", "mrope_section": [2, 3, 3]},
            rope_theta=10000.0, eos_token_id=1),
        vision_config=dict(depth=2, embed_dim=32, hidden_size=64,
                           num_heads=2, in_channels=3, patch_size=4,
                           spatial_merge_size=2, temporal_patch_size=2),
        image_token_id=IMG_TOK, video_token_id=VID_TOK,
        vision_start_token_id=VSTART, vision_end_token_id=VEND)


@pytest.fixture(scope="module")
def hf_model():
    torch.manual_seed(0)
    return Qwen2VLForConditionalGeneration(tiny_cfg()).eval()


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory, hf_model):
    path = tmp_path_factory.mktemp("tiny_qwen2_vl")
    hf_model.save_pretrained(path, safe_serialization=True)
    return str(path)


def _patches(rng, t, h, w):
    """Flattened conv patches [t*h*w, C*tp*ps*ps] (grid in patch
    units; t is TEMPORAL PATCHES, i.e. frames/temporal_patch_size)."""
    return rng.standard_normal((t * h * w, 3 * 2 * 4 * 4)).astype(
        np.float32)


def test_vision_tower_matches_hf(ckpt, hf_model):
    from vllm_distributed_tpu.multimodal.qwen2_vision import \
        build_qwen2_vision_encoder
    enc = build_qwen2_vision_encoder(ckpt, hf_model.config)
    assert enc is not None
    rng = np.random.default_rng(0)
    pix = _patches(rng, 1, 4, 8)
    grid = [(1, 4, 8)]
    got = enc.encode(pix, grid)
    with torch.no_grad():
        want = hf_model.model.visual(
            torch.tensor(pix), grid_thw=torch.tensor(grid)).numpy()
    assert len(got) == 1 and got[0].shape == want.shape
    np.testing.assert_allclose(got[0], want, atol=2e-4, rtol=2e-3)


def test_vision_tower_batches_image_and_video(ckpt, hf_model):
    """Two inputs (one multi-frame video, one image) in one call:
    block-diagonal attention must keep them independent."""
    from vllm_distributed_tpu.multimodal.qwen2_vision import \
        build_qwen2_vision_encoder
    enc = build_qwen2_vision_encoder(ckpt, hf_model.config)
    rng = np.random.default_rng(1)
    vid = _patches(rng, 2, 4, 4)   # 2 temporal patches (4 frames)
    img = _patches(rng, 1, 4, 4)
    both = np.concatenate([vid, img])
    grids = [(2, 4, 4), (1, 4, 4)]
    got = enc.encode(both, grids)
    with torch.no_grad():
        want = hf_model.model.visual(
            torch.tensor(both), grid_thw=torch.tensor(grids)).numpy()
    n_vid = 2 * 4 * 4 // 4
    np.testing.assert_allclose(got[0], want[:n_vid], atol=2e-4,
                               rtol=2e-3)
    np.testing.assert_allclose(got[1], want[n_vid:], atol=2e-4,
                               rtol=2e-3)
    # Independence: the image's rows match a solo encode exactly.
    solo = enc.encode(img, [(1, 4, 4)])[0]
    np.testing.assert_allclose(got[1], solo, atol=1e-5)


def test_mrope_positions_match_hf(hf_model):
    from vllm_distributed_tpu.multimodal import (MultiModalInput,
                                                 compute_mrope_positions)
    # Prompt: 3 text, image (2x2 merged = 4 tokens), 2 text.
    ids = [5, 6, VSTART] + [IMG_TOK] * 4 + [VEND, 7]
    mm = [MultiModalInput(embeds=np.zeros((4, 64), np.float32),
                          offset=3, grid=(1, 2, 2))]
    pos, delta = compute_mrope_positions(len(ids), mm)
    with torch.no_grad():
        want, rope_delta = hf_model.model.get_rope_index(
            torch.tensor([ids]),
            image_grid_thw=torch.tensor([[1, 4, 4]]))
    np.testing.assert_array_equal(pos.T, want[:, 0].numpy())
    assert delta == int(rope_delta[0])


def _run_engine(path, prompt, mm, n=6, **overrides):
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=128,
                max_num_batched_tokens=128, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    engine = LLMEngine(EngineArgs(**args).create_engine_config())
    sp = SamplingParams(temperature=0.0, max_tokens=n, ignore_eos=True)
    engine.add_request("q-0", prompt, sp, multi_modal_data=mm)
    for _ in range(200):
        for out in engine.step():
            if out.finished:
                return out.outputs[0].token_ids
    raise AssertionError("did not finish")


def _hf_greedy(hf_model, ids, n, pix=None, grid=None, videos=None,
               vgrid=None):
    ids = list(ids)
    kw = {}
    if pix is not None:
        kw["pixel_values"] = torch.tensor(pix)
        kw["image_grid_thw"] = torch.tensor(grid)
    if videos is not None:
        kw["pixel_values_videos"] = torch.tensor(videos)
        kw["video_grid_thw"] = torch.tensor(vgrid)
    with torch.no_grad():
        out = []
        for _ in range(n):
            logits = hf_model(input_ids=torch.tensor([ids]),
                              **kw).logits
            nxt = int(logits[0, -1].argmax())
            out.append(nxt)
            ids.append(nxt)
        return out


def test_image_e2e_greedy_matches_hf(ckpt, hf_model):
    rng = np.random.default_rng(2)
    pix = _patches(rng, 1, 4, 4)
    grid = [(1, 4, 4)]
    # Engine prompt: ONE placeholder, expanded by the processor.
    prompt = [5, 6, VSTART, IMG_TOK, VEND, 7, 8]
    got = _run_engine(ckpt, prompt,
                      {"pixel_values": pix, "image_grid_thw": grid})
    # HF prompt: the expanded form (4 merged tokens).
    hf_ids = [5, 6, VSTART] + [IMG_TOK] * 4 + [VEND, 7, 8]
    want = _hf_greedy(hf_model, hf_ids, 6, pix=pix, grid=grid)
    assert got == want


def test_video_e2e_greedy_matches_hf(ckpt, hf_model):
    rng = np.random.default_rng(3)
    vid = _patches(rng, 2, 4, 4)
    vgrid = [(2, 4, 4)]
    prompt = [9, VSTART, VID_TOK, VEND, 11]
    got = _run_engine(
        ckpt, prompt,
        {"pixel_values_videos": vid, "video_grid_thw": vgrid})
    hf_ids = [9, VSTART] + [VID_TOK] * 8 + [VEND, 11]
    want = _hf_greedy(hf_model, hf_ids, 6, videos=vid, vgrid=vgrid)
    assert got == want


def test_text_only_matches_hf(ckpt, hf_model):
    """No images: M-RoPE with equal ids must equal plain rope."""
    prompt = [5, 9, 23, 40, 77, 12]
    got = _run_engine(ckpt, prompt, None)
    want = _hf_greedy(hf_model, prompt, 6)
    assert got == want


def test_qwen2_vl_prefix_cache_salted_by_media(ckpt):
    """Two requests with the SAME video share prefix-cache pages; a
    DIFFERENT video must not (the mm content hash salts the block
    hashes), and M-RoPE tables stay per-request correct across cache
    hits."""
    rng = np.random.default_rng(5)
    vid_a = _patches(rng, 2, 4, 4)
    vid_b = _patches(rng, 2, 4, 4)
    vgrid = [(2, 4, 4)]
    prompt = [9, VSTART, VID_TOK, VEND, 11, 12, 13, 14]

    def mm(v):
        return {"pixel_values_videos": v, "video_grid_thw": vgrid}

    engine = LLMEngine(EngineArgs(
        model=ckpt, dtype="float32", block_size=4,
        num_gpu_blocks_override=128, max_model_len=128,
        max_num_batched_tokens=128, max_num_seqs=8,
        enable_prefix_caching=True,
        skip_tokenizer_init=True).create_engine_config())
    sp = SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True)

    def one(tag, v):
        engine.add_request(tag, prompt, sp, multi_modal_data=mm(v))
        for _ in range(200):
            for out in engine.step():
                if out.finished:
                    return out
        raise AssertionError("did not finish")

    first = one("va-0", vid_a)
    again = one("va-1", vid_a)
    other = one("vb-0", vid_b)
    # Same video: identical output AND a cache hit; different video:
    # different continuation (same token prompt!) — no false sharing.
    assert again.outputs[0].token_ids == first.outputs[0].token_ids
    assert again.num_cached_tokens > 0
    assert other.num_cached_tokens == 0
    assert other.outputs[0].token_ids != first.outputs[0].token_ids
