"""Tiny-DeepSeek parity vs HF through the full engine: MLA (latent KV
cache, absorbed decode path), q LoRA projections, group-limited and
noaux_tc routing, shared experts (model: reference
vllm/model_executor/models/deepseek_v2.py + the MLA backends,
v1/attention/backends/mla/common.py)."""

import numpy as np
import pytest
import torch
from transformers import DeepseekV2Config, DeepseekV3Config
from transformers import DeepseekV2ForCausalLM as HFDeepseekV2
from transformers import DeepseekV3ForCausalLM as HFDeepseekV3

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

PROMPTS = [
    [3, 17, 92, 45, 8],
    [5, 9, 33, 71],
    [11, 12, 13, 14, 15, 16],
]

_V2_DIMS = dict(
    vocab_size=128, hidden_size=64, intermediate_size=96,
    moe_intermediate_size=48, num_hidden_layers=3,
    num_attention_heads=4, num_key_value_heads=4,
    q_lora_rank=None, kv_lora_rank=32, qk_nope_head_dim=16,
    qk_rope_head_dim=8, v_head_dim=16, n_routed_experts=4,
    num_experts_per_tok=2, n_shared_experts=1, first_k_dense_replace=1,
    routed_scaling_factor=1.0, topk_method="greedy", n_group=1,
    topk_group=1, norm_topk_prob=False, max_position_embeddings=64,
    eos_token_id=1, head_dim=8,
)


def _save(tmp_path_factory, hf_cls, cfg, tag):
    torch.manual_seed(0)
    hf = hf_cls(cfg).eval()
    path = tmp_path_factory.mktemp(tag)
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


@pytest.fixture(scope="module")
def v2_checkpoint(tmp_path_factory):
    return _save(tmp_path_factory, HFDeepseekV2,
                 DeepseekV2Config(**_V2_DIMS), "tiny_dsv2")


@pytest.fixture(scope="module")
def v2_qlora_grouped_checkpoint(tmp_path_factory):
    dims = dict(_V2_DIMS, q_lora_rank=24,
                topk_method="group_limited_greedy", n_group=2,
                topk_group=1, routed_scaling_factor=2.0)
    return _save(tmp_path_factory, HFDeepseekV2,
                 DeepseekV2Config(**dims), "tiny_dsv2q")


@pytest.fixture(scope="module")
def v3_checkpoint(tmp_path_factory):
    dims = dict(_V2_DIMS, q_lora_rank=24, n_group=2, topk_group=2,
                norm_topk_prob=True, routed_scaling_factor=1.5)
    dims.pop("topk_method")
    cfg = DeepseekV3Config(**dims)
    torch.manual_seed(0)
    hf = HFDeepseekV3(cfg).eval()
    # Exercise the aux-loss-free correction bias (zeros at init).
    with torch.no_grad():
        for block in hf.model.layers[cfg.first_k_dense_replace:]:
            block.mlp.gate.e_score_correction_bias.uniform_(-0.05, 0.05)
    path = tmp_path_factory.mktemp("tiny_dsv3")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def hf_greedy(hf, prompt, n):
    with torch.no_grad():
        out = hf.generate(torch.tensor([prompt]), max_new_tokens=n,
                          do_sample=False, eos_token_id=None)
    return out[0].tolist()[len(prompt):]


def run(engine, prompts, tag, max_tokens=6):
    sps = [SamplingParams(temperature=0.0, max_tokens=max_tokens,
                          ignore_eos=True) for _ in prompts]
    for i, (p, sp) in enumerate(zip(prompts, sps)):
        engine.add_request(f"{tag}-{i}", p, sp)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k].outputs[0].token_ids for k in order]


def test_v2_greedy_matches_hf(v2_checkpoint):
    path, hf = v2_checkpoint
    got = run(make_engine(path), PROMPTS, "ds")
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_v2_qlora_grouped_routing_matches_hf(v2_qlora_grouped_checkpoint):
    """q_a/q_b low-rank query path + group-limited-greedy expert
    selection + routed scaling."""
    path, hf = v2_qlora_grouped_checkpoint
    got = run(make_engine(path), PROMPTS, "dsq")
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_v3_noaux_tc_matches_hf(v3_checkpoint):
    """V3 sigmoid scoring + correction bias + top-2-sum group select +
    normalized weights."""
    path, hf = v3_checkpoint
    got = run(make_engine(path), PROMPTS, "ds3")
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_v2_tp2_matches_hf(v2_checkpoint):
    """MLA under tensor parallelism: q heads shard, the latent cache
    replicates (MQA), experts run TP-inside-FFN."""
    path, hf = v2_checkpoint
    got = run(make_engine(path, tensor_parallel_size=2), PROMPTS, "dstp")
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_v2_expert_parallel_matches_hf(v2_checkpoint):
    path, hf = v2_checkpoint
    got = run(make_engine(path, tensor_parallel_size=2,
                          enable_expert_parallel=True), PROMPTS, "dsep")
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_v2_prefill_logprobs_match_hf(v2_checkpoint):
    path, hf = v2_checkpoint
    engine = make_engine(path)
    prompt = PROMPTS[0]
    k = 5
    engine.add_request("lg-0", prompt,
                       SamplingParams(temperature=0.0, max_tokens=1,
                                      ignore_eos=True, logprobs=k))
    outs = []
    for _ in range(50):
        outs += [o for o in engine.step() if o.finished]
        if not engine.has_unfinished_requests():
            break
    (out, ) = outs
    got = out.outputs[0].logprobs[0]
    with torch.no_grad():
        hf_logits = hf(torch.tensor([prompt])).logits[0, -1]
    hf_lp = torch.log_softmax(hf_logits.float(), dim=-1)
    want_vals, want_ids = torch.topk(hf_lp, k)
    assert set(got) >= set(want_ids.tolist())
    for tok, val in zip(want_ids.tolist(), want_vals.tolist()):
        assert abs(got[tok] - val) < 5e-3, (tok, got[tok], val)


def test_latent_cache_is_an_order_smaller(v2_checkpoint):
    """The point of MLA: page bytes store Lkv+R per token instead of
    2 * heads * head_dim per layer."""
    path, _ = v2_checkpoint
    engine = make_engine(path)
    runner = (engine.engine_core.engine_core.executor
              .worker.model_runner)
    model = runner.model
    c = model.cfg
    page = 4
    latent = model.kv_cache_page_bytes(page)
    # A same-shape GQA cache would cost 2 * kv_heads * head_dim wide
    # rows; the latent row is kv_lora_rank + rope dim.
    dense_equiv = (2 * c.num_layers * page * 4 * (16 + 8) *
                   np.dtype(np.float32).itemsize)
    assert latent < dense_equiv
    caches = model.make_kv_caches(8, page)
    assert set(caches) == {"c"}
    assert caches["c"].shape == (c.num_layers, 8, page, 32 + 8)


def test_chunked_prefill_matches_hf(v2_checkpoint):
    """Long prompt fed through a small token budget: the absorbed MLA
    path must be exact under chunked prefill."""
    path, hf = v2_checkpoint
    engine = make_engine(path, max_num_batched_tokens=8)
    prompt = list(range(2, 34))
    got = run(engine, [prompt], "dschunk")
    want = [hf_greedy(hf, prompt, 6)]
    assert got == want


def test_yarn_rope_matches_transformers():
    """yarn_inv_freq mirrors transformers' _compute_yarn_parameters
    (DeepSeek checkpoints ship yarn rope_scaling with mscale factors)."""
    import torch as _torch
    from transformers import LlamaConfig as _Cfg
    from transformers.modeling_rope_utils import _compute_yarn_parameters

    from vllm_distributed_tpu.models.common import yarn_inv_freq

    scaling = {"rope_type": "yarn", "factor": 40.0,
               "original_max_position_embeddings": 4096,
               "mscale": 1.0, "mscale_all_dim": 1.0,
               "beta_fast": 32, "beta_slow": 1}
    cfg = _Cfg(rope_theta=10000.0, hidden_size=512,
               num_attention_heads=8, head_dim=64,
               max_position_embeddings=163840, rope_scaling=dict(scaling))
    want_freq, want_att = _compute_yarn_parameters(cfg, _torch.device("cpu"))
    got_freq, got_att = yarn_inv_freq(64, 10000.0, scaling, 163840)
    np.testing.assert_allclose(np.asarray(got_freq),
                               want_freq.numpy(), rtol=1e-6)
    assert abs(got_att - want_att) < 1e-9


def test_v3_yarn_mscale_matches_hf(tmp_path_factory):
    """Real V3/R1 checkpoints ship yarn rope_scaling with mscale_all_dim;
    HF folds yarn mscale^2 into the attention scale for V3 (and only
    V3) — parity locks both the scaled scores and the yarn cos/sin."""
    dims = dict(_V2_DIMS, q_lora_rank=24, n_group=2, topk_group=2,
                norm_topk_prob=True,
                rope_scaling={"rope_type": "yarn", "factor": 4.0,
                              "mscale": 1.0, "mscale_all_dim": 1.0,
                              "original_max_position_embeddings": 16,
                              "beta_fast": 32, "beta_slow": 1})
    dims.pop("topk_method")
    path, hf = _save(tmp_path_factory, HFDeepseekV3,
                     DeepseekV3Config(**dims), "tiny_dsv3_yarn")
    got = run(make_engine(path), PROMPTS, "ds3y")
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want


def test_v2_pallas_backend_matches_hf(v2_checkpoint, monkeypatch):
    """End to end on the Pallas backend (interpret): the latent kernel
    (ops/pallas_mla.py) carries the MLA attention."""
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    monkeypatch.setenv("VDT_PALLAS_INTERPRET", "1")
    path, hf = v2_checkpoint
    got = run(make_engine(path, block_size=8), PROMPTS, "dspl")
    want = [hf_greedy(hf, p, 6) for p in PROMPTS]
    assert got == want
