"""Encoder-only (BERT/RoBERTa) parity + e2e embedding/scoring tests
(reference pattern: the embedding-model parity tests of the reference's
tests/models/embedding/, exercising BertEmbeddingModel / cross-encoder
checkpoints through the engine)."""

import numpy as np
import pytest
import torch
import transformers

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.models.bert import (BertEmbeddingModel,
                                              BertForSequenceClassification,
                                              RobertaEmbeddingModel)
from vllm_distributed_tpu.models.llama import LlamaArchConfig
from vllm_distributed_tpu.sampling_params import SamplingParams

import jax.numpy as jnp


def _tiny_bert_cfg(**kw):
    return transformers.BertConfig(
        vocab_size=97, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2,
        hidden_act="gelu", **kw)


def _build(model_cls, hf_model, hf_cfg):
    arch = LlamaArchConfig.from_hf_config(
        model_cls.arch_config_source(hf_cfg), dtype=jnp.float32)
    model_cls.configure_arch(arch, hf_cfg)
    model = model_cls(arch)
    sd = {k: v.numpy() for k, v in hf_model.state_dict().items()}
    params = model.params_from_hf_state_dict(sd, dtype=jnp.float32)
    return model, params


def _pad_batch(prompts, L):
    R = len(prompts)
    ids = np.zeros((R, L), np.int32)
    valid = np.zeros((R, L), bool)
    for i, p in enumerate(prompts):
        ids[i, :len(p)] = p
        valid[i, :len(p)] = True
    return ids, valid


PROMPTS = [[2, 17, 45, 8, 21, 5], [2, 9, 33, 5], [2, 7, 5]]


def test_bert_hidden_state_matches_hf():
    cfg = _tiny_bert_cfg()
    torch.manual_seed(0)
    hf = transformers.BertModel(cfg)
    hf.eval()
    model, params = _build(BertEmbeddingModel, hf, cfg)

    L = 8
    ids, valid = _pad_batch(PROMPTS, L)
    hidden = model.encode(params, jnp.asarray(ids),
                          jnp.zeros_like(jnp.asarray(ids)),
                          jnp.asarray(valid))
    with torch.no_grad():
        out = hf(input_ids=torch.tensor(ids, dtype=torch.long),
                 attention_mask=torch.tensor(valid, dtype=torch.long))
    ref = out.last_hidden_state.numpy()
    for i, p in enumerate(PROMPTS):
        np.testing.assert_allclose(np.asarray(hidden)[i, :len(p)],
                                   ref[i, :len(p)], atol=2e-4, rtol=2e-3)

    # Pooling variants agree with their definitions on the valid span.
    pooled = model.pool(params, hidden, jnp.asarray(valid))
    for i, p in enumerate(PROMPTS):
        np.testing.assert_allclose(np.asarray(pooled["cls"])[i],
                                   ref[i, 0], atol=2e-4, rtol=2e-3)
        np.testing.assert_allclose(np.asarray(pooled["mean"])[i],
                                   ref[i, :len(p)].mean(0), atol=2e-4,
                                   rtol=2e-3)
        np.testing.assert_allclose(np.asarray(pooled["last"])[i],
                                   ref[i, len(p) - 1], atol=2e-4,
                                   rtol=2e-3)


def test_bert_cross_encoder_score_matches_hf():
    cfg = _tiny_bert_cfg(num_labels=1)
    torch.manual_seed(1)
    hf = transformers.BertForSequenceClassification(cfg)
    hf.eval()
    model, params = _build(BertForSequenceClassification, hf, cfg)

    L = 8
    ids, valid = _pad_batch(PROMPTS, L)
    type_ids = np.zeros((len(PROMPTS), L), np.int32)
    type_ids[0, 3:6] = 1  # second segment of a (query, doc) pair
    hidden = model.encode(params, jnp.asarray(ids),
                          jnp.asarray(type_ids), jnp.asarray(valid))
    pooled = model.pool(params, hidden, jnp.asarray(valid))
    with torch.no_grad():
        out = hf(input_ids=torch.tensor(ids, dtype=torch.long),
                 token_type_ids=torch.tensor(type_ids, dtype=torch.long),
                 attention_mask=torch.tensor(valid, dtype=torch.long))
    np.testing.assert_allclose(np.asarray(pooled["logits"]),
                               out.logits.numpy(), atol=2e-4, rtol=2e-3)
    # Single-logit heads score through sigmoid, matching HF's
    # get_cross_encoder_activation_function for num_labels == 1.
    np.testing.assert_allclose(
        np.asarray(pooled["score"]),
        torch.sigmoid(out.logits[:, 0]).numpy(), atol=2e-4, rtol=2e-3)


def test_roberta_position_offset_matches_hf():
    cfg = transformers.RobertaConfig(
        vocab_size=97, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=68, type_vocab_size=1,
        pad_token_id=1)
    torch.manual_seed(2)
    hf = transformers.RobertaModel(cfg)
    hf.eval()
    model, params = _build(RobertaEmbeddingModel, hf, cfg)

    L = 8
    ids, valid = _pad_batch(PROMPTS, L)
    hidden = model.encode(params, jnp.asarray(ids),
                          jnp.zeros_like(jnp.asarray(ids)),
                          jnp.asarray(valid))
    # HF roberta computes positions from the attention mask (offset by
    # padding_idx + 1 = 2 for left-aligned rows, same as our arange).
    with torch.no_grad():
        out = hf(input_ids=torch.tensor(ids, dtype=torch.long),
                 attention_mask=torch.tensor(valid, dtype=torch.long))
    ref = out.last_hidden_state.numpy()
    for i, p in enumerate(PROMPTS):
        np.testing.assert_allclose(np.asarray(hidden)[i, :len(p)],
                                   ref[i, :len(p)], atol=2e-4, rtol=2e-3)


# ---------------------------------------------------------------------------
# End-to-end through the engine (encoder runner + scheduler).
# ---------------------------------------------------------------------------
def _save(tmp_path_factory, name, hf):
    path = str(tmp_path_factory.mktemp(name))
    hf.save_pretrained(path, safe_serialization=True)
    return path


@pytest.fixture(scope="module")
def bert_ckpt(tmp_path_factory):
    cfg = _tiny_bert_cfg()
    torch.manual_seed(3)
    hf = transformers.BertModel(cfg)
    hf.eval()
    return _save(tmp_path_factory, "tiny_bert", hf), hf


@pytest.fixture(scope="module")
def cross_encoder_ckpt(tmp_path_factory):
    cfg = _tiny_bert_cfg(num_labels=1)
    torch.manual_seed(4)
    hf = transformers.BertForSequenceClassification(cfg)
    hf.eval()
    return _save(tmp_path_factory, "tiny_cross", hf), hf


def _make_engine(path, **overrides):
    args = dict(model=path, dtype="float32", block_size=4,
                max_model_len=32, max_num_batched_tokens=64,
                max_num_seqs=8, skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def _run_pooling(engine, prompts, pooling_list):
    sp = SamplingParams(temperature=0.0, max_tokens=1, ignore_eos=True)
    for i, (p, pool) in enumerate(zip(prompts, pooling_list)):
        engine.add_request(f"e-{i}", p, sp, pooling_params=pool)
    done = {}
    for _ in range(100):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    return [np.asarray(done[f"e-{i}"].embedding, np.float32)
            for i in range(len(prompts))]


def test_encoder_e2e_embeddings_match_hf(bert_ckpt):
    path, hf = bert_ckpt
    engine = _make_engine(path)
    embs = _run_pooling(
        engine, PROMPTS,
        [{"type": "cls"}, {"type": "mean"}, {"type": "cls"}])
    L = max(len(p) for p in PROMPTS)
    ids, valid = _pad_batch(PROMPTS, L)
    with torch.no_grad():
        ref = hf(input_ids=torch.tensor(ids, dtype=torch.long),
                 attention_mask=torch.tensor(valid, dtype=torch.long)
                 ).last_hidden_state.numpy()
    np.testing.assert_allclose(embs[0], ref[0, 0], atol=5e-4, rtol=5e-3)
    np.testing.assert_allclose(
        embs[1], ref[1, :len(PROMPTS[1])].mean(0), atol=5e-4, rtol=5e-3)
    np.testing.assert_allclose(embs[2], ref[2, 0], atol=5e-4, rtol=5e-3)


def test_encoder_e2e_generate_rejected(bert_ckpt):
    path, _ = bert_ckpt
    engine = _make_engine(path)
    with pytest.raises(ValueError, match="encoder-only"):
        engine.add_request(
            "g-0", [2, 7, 5],
            SamplingParams(temperature=0.0, max_tokens=4))


def test_score_pooling_rejected_without_head(bert_ckpt):
    """'score' on a plain embedding checkpoint must 400 at admission —
    a runner-side raise would kill the engine core for everyone."""
    path, _ = bert_ckpt
    engine = _make_engine(path)
    with pytest.raises(ValueError, match="classification"):
        engine.add_request(
            "s-0", [2, 7, 5],
            SamplingParams(temperature=0.0, max_tokens=1),
            pooling_params={"type": "score"})
    # The engine survives and still serves embedding requests.
    embs = _run_pooling(engine, [PROMPTS[2]], [{"type": "cls"}])
    assert len(embs[0]) == 32


def test_roberta_prompt_beyond_position_capacity_rejected(
        tmp_path_factory):
    """RoBERTa's position table minus its offset bounds admissible
    prompts (a 20-row table with offset 2 holds 18 tokens)."""
    cfg = transformers.RobertaConfig(
        vocab_size=97, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=20, type_vocab_size=1, pad_token_id=1)
    torch.manual_seed(5)
    hf = transformers.RobertaModel(cfg)
    path = _save(tmp_path_factory, "tiny_roberta_cap", hf)
    engine = _make_engine(path, max_model_len=20)
    sp = SamplingParams(temperature=0.0, max_tokens=1)
    with pytest.raises(ValueError, match="position capacity"):
        engine.add_request("c-0", list(range(2, 21)), sp,
                           pooling_params={"type": "cls"})
    # 18 tokens fit.
    embs = _run_pooling(engine, [list(range(2, 20))], [{"type": "cls"}])
    assert len(embs[0]) == 32


def test_llm_score_uses_cross_encoder_head(cross_encoder_ckpt):
    """LLM.score on a classification checkpoint runs the pair through
    the head (reference: the cross-encoder mode of LLM.score)."""
    from vllm_distributed_tpu.entrypoints.llm import LLM
    path, hf = cross_encoder_ckpt
    llm = LLM(model=path, dtype="float32", block_size=4,
              max_model_len=32, max_num_batched_tokens=64,
              max_num_seqs=8, skip_tokenizer_init=True)
    q, d = [2, 17, 45], [60, 8, 21, 5]
    scores = llm.score([q], [d])
    with torch.no_grad():
        ids = torch.tensor([q + d], dtype=torch.long)
        tt = torch.tensor([[0] * len(q) + [1] * len(d)], dtype=torch.long)
        ref = torch.sigmoid(
            hf(input_ids=ids, token_type_ids=tt).logits[0, 0]).item()
    assert len(scores) == 1
    np.testing.assert_allclose(scores[0], ref, atol=5e-4, rtol=5e-3)


def test_cross_encoder_e2e_score_matches_hf(cross_encoder_ckpt):
    path, hf = cross_encoder_ckpt
    engine = _make_engine(path)
    pair = [2, 17, 45, 60, 8, 21, 5]           # [CLS] q [SEP] d [SEP]
    tt = [0, 0, 0, 0, 1, 1, 1]
    embs = _run_pooling(engine, [pair],
                        [{"type": "score", "token_type_ids": tt}])
    with torch.no_grad():
        out = hf(input_ids=torch.tensor([pair], dtype=torch.long),
                 token_type_ids=torch.tensor([tt], dtype=torch.long))
    assert len(embs[0]) == 1
    np.testing.assert_allclose(
        embs[0][0], torch.sigmoid(out.logits[0, 0]).item(),
        atol=5e-4, rtol=5e-3)


def test_encoder_e2e_tp2_matches_single_device(bert_ckpt):
    """GSPMD TP over the dense encoder: head/ffn sharding must not
    change the pooled embeddings."""
    path, _ = bert_ckpt
    single = _make_engine(path)
    tp2 = _make_engine(path, tensor_parallel_size=2)
    e1 = _run_pooling(single, [PROMPTS[0]], [{"type": "cls"}])[0]
    e2 = _run_pooling(tp2, [PROMPTS[0]], [{"type": "cls"}])[0]
    np.testing.assert_allclose(e1, e2, atol=1e-5, rtol=1e-5)
