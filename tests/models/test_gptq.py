"""GPTQ checkpoint loading (reference:
quantization/gptq.py runtime kernels -> here host-side
dequantize-on-load): pack/unpack roundtrip against the documented
formula, and engine equivalence between a packed GPTQ checkpoint and
the same weights stored dequantized."""

import json
import os

import numpy as np
import pytest
import torch
from safetensors.numpy import save_file
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.models.gptq import (dequantize_gptq_layer,
                                              maybe_dequantize_gptq)
from vllm_distributed_tpu.sampling_params import SamplingParams

BITS, GROUP = 4, 16


def _pack(vals, bits, axis):
    """AutoGPTQ packing: 32/bits unsigned values per int32 word along
    ``axis``, low bits first."""
    pack = 32 // bits
    vals = np.moveaxis(vals.astype(np.uint32), axis, -1)
    shape = vals.shape[:-1] + (vals.shape[-1] // pack, pack)
    vals = vals.reshape(shape)
    shifts = np.arange(pack, dtype=np.uint32) * bits
    words = (vals << shifts).sum(axis=-1).astype(np.uint32)
    # safetensors serializes the raw buffer: must be C-contiguous.
    return np.ascontiguousarray(
        np.moveaxis(words, -1, axis).astype(np.int32))


def quantize_gptq(w, bits=BITS, group=GROUP):
    """Groupwise-quantize a torch-orientation [out, in] matrix into the
    AutoGPTQ v1 tensor set (asymmetric, zero stored minus one)."""
    out_dim, in_dim = w.shape
    maxq = (1 << bits) - 1
    wg = w.T.reshape(in_dim // group, group, out_dim)  # [G, g, out]
    wmin, wmax = wg.min(axis=1), wg.max(axis=1)        # [G, out]
    scales = np.maximum((wmax - wmin) / maxq, 1e-8)
    zeros = np.clip(np.round(-wmin / scales), 0, maxq)
    q = np.clip(np.round(wg / scales[:, None]) + zeros[:, None], 0,
                maxq).astype(np.uint32)                # [G, g, out]
    q = q.reshape(in_dim, out_dim)
    return {
        "qweight": _pack(q, bits, axis=0),
        "qzeros": _pack((zeros - 1).astype(np.uint32) & maxq, bits,
                        axis=1),
        "scales": np.ascontiguousarray(scales.astype(np.float16)),
        "g_idx": np.ascontiguousarray(
            (np.arange(in_dim) // group).astype(np.int32)),
    }, (scales[(np.arange(in_dim) // group)]
        * (q.astype(np.float32)
           - zeros[(np.arange(in_dim) // group)])).T  # dequant [out, in]


def test_pack_dequant_roundtrip():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((24, 32)).astype(np.float32)  # [out, in]
    packed, expect = quantize_gptq(w)
    got = dequantize_gptq_layer(packed["qweight"], packed["qzeros"],
                                packed["scales"], packed["g_idx"],
                                BITS, GROUP)
    np.testing.assert_allclose(got, expect, rtol=1e-3, atol=1e-3)
    # The quantization grid reconstructs the original within one step.
    assert np.abs(got - w).max() <= packed["scales"].astype(
        np.float32).max() * 0.51 + 1e-6


def test_rejects_unknown_quant_methods():
    class Cfg:
        quantization_config = {"quant_method": "squeezellm"}
    with pytest.raises(ValueError, match="only 'gptq' and 'awq'"):
        maybe_dequantize_gptq({}, Cfg())


CFG = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=2, max_position_embeddings=64,
           eos_token_id=1)
TARGETS = ("self_attn.q_proj", "self_attn.k_proj", "self_attn.v_proj",
           "self_attn.o_proj", "mlp.gate_proj", "mlp.up_proj",
           "mlp.down_proj")


def _run(path, **overrides):
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    engine = LLMEngine(EngineArgs(**args).create_engine_config())
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    engine.add_request("r", [3, 17, 92, 45, 8], sp)
    for _ in range(100):
        for out in engine.step():
            if out.finished:
                return out.outputs[0].token_ids
    raise AssertionError("did not finish")


def test_gptq_checkpoint_matches_dequantized_fp(tmp_path_factory):
    torch.manual_seed(0)
    hf = HFLlama(LlamaConfig(**CFG))
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}

    packed_sd, fp_sd = {}, {}
    for name, w in sd.items():
        if any(name.endswith(f"{t}.weight") for t in TARGETS):
            base = name[:-len(".weight")]
            packed, _ = quantize_gptq(w.astype(np.float32))
            for suffix, arr in packed.items():
                packed_sd[f"{base}.{suffix}"] = arr
            # Expected fp checkpoint = the loader's own dequant (incl.
            # the fp16 rounding of stored scales), so the two engines
            # see bit-identical weights.
            fp_sd[name] = dequantize_gptq_layer(
                packed["qweight"], packed["qzeros"], packed["scales"],
                packed["g_idx"], BITS, GROUP).astype(np.float32)
        else:
            packed_sd[name] = w
            fp_sd[name] = w

    def save(sdict, name, quantized):
        path = str(tmp_path_factory.mktemp(name))
        save_file({k: np.ascontiguousarray(v) for k, v in sdict.items()},
                  os.path.join(path, "model.safetensors"))
        cfg = dict(CFG, architectures=["LlamaForCausalLM"],
                   model_type="llama")
        if quantized:
            cfg["quantization_config"] = {
                "quant_method": "gptq", "bits": BITS,
                "group_size": GROUP, "desc_act": False, "sym": False}
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(cfg, f)
        return path

    gptq_path = save(packed_sd, "tiny_gptq", True)
    got = _run(gptq_path)
    want = _run(save(fp_sd, "tiny_gptq_fp", False))
    assert got == want
    # GPTQ dequant composes with w8a16 requantization (--quantization):
    # the doubly-quantized engine still agrees on the first greedy token.
    q8 = _run(gptq_path, quantization="int8")
    assert q8[0] == want[0]


def test_group_size_minus_one_single_group():
    """group_size=-1 (one group over the whole input dim) with the
    trivial g_idx stripped must dequantize, not index negatively."""
    rng = np.random.default_rng(1)
    w = rng.standard_normal((8, 32)).astype(np.float32)
    packed, _ = quantize_gptq(w, bits=4, group=32)  # one group
    got = dequantize_gptq_layer(packed["qweight"], packed["qzeros"],
                                packed["scales"], None, 4, -1)
    assert np.abs(got - w).max() <= packed["scales"].astype(
        np.float32).max() * 0.51 + 1e-6


def test_legacy_quantize_config_json(tmp_path):
    """Pre-integration AutoGPTQ layout: quantize_config.json beside the
    shards, nothing in config.json."""
    rng = np.random.default_rng(2)
    w = rng.standard_normal((24, 32)).astype(np.float32)
    packed, _ = quantize_gptq(w)
    with open(tmp_path / "quantize_config.json", "w") as f:
        json.dump({"bits": BITS, "group_size": GROUP}, f)

    class Cfg:
        quantization_config = None
    tensors = {f"model.layers.0.self_attn.q_proj.{k}": v
               for k, v in packed.items()}
    out = maybe_dequantize_gptq(tensors, Cfg(), str(tmp_path))
    got = out["model.layers.0.self_attn.q_proj.weight"]
    assert np.abs(got - w).max() <= packed["scales"].astype(
        np.float32).max() * 0.51 + 1e-6


def test_packed_tensors_without_any_config_rejected():
    class Cfg:
        quantization_config = None
    with pytest.raises(ValueError, match="cannot identify"):
        maybe_dequantize_gptq({"x.qweight": np.zeros((1, 8), np.int32)},
                              Cfg(), "/nonexistent")


def test_gptq_native_int4g_serving_matches_fp(tmp_path_factory):
    """--quantization gptq (int4g): group-wise asymmetric uint4
    serving. The loader's fp reconstruction lies exactly on each
    group's 4-bit lattice, so the re-quantization is lossless and the
    greedy output matches the full-precision engine exactly while the
    weights stay 4-bit in HBM."""
    torch.manual_seed(3)
    hf = HFLlama(LlamaConfig(**CFG))
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    packed_sd = {}
    for name, w in sd.items():
        if any(name.endswith(f"{t}.weight") for t in TARGETS):
            base = name[:-len(".weight")]
            packed, _ = quantize_gptq(w.astype(np.float32))
            for suffix, arr in packed.items():
                packed_sd[f"{base}.{suffix}"] = arr
        else:
            packed_sd[name] = w
    path = str(tmp_path_factory.mktemp("tiny_gptq_native"))
    save_file({k: np.ascontiguousarray(v) for k, v in packed_sd.items()},
              os.path.join(path, "model.safetensors"))
    cfg = dict(CFG, architectures=["LlamaForCausalLM"],
               model_type="llama")
    cfg["quantization_config"] = {
        "quant_method": "gptq", "bits": BITS, "group_size": GROUP,
        "desc_act": False, "sym": False}
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(cfg, f)

    want = _run(path)  # fp serving of the dequantized checkpoint
    got = _run(path, quantization="gptq")  # native 4-bit serving
    # The reconstruction differs from fp only by ~1 ulp on the group
    # scale; random-weight logits have near-ties, so compare a greedy
    # prefix here and assert losslessness at the weight level below.
    assert got[:4] == want[:4]

    import jax.numpy as jnp
    import numpy as np2
    from vllm_distributed_tpu.engine.arg_utils import EngineArgs as EA

    def runner_of(**overrides):
        args = dict(model=path, dtype="float32", block_size=4,
                    num_gpu_blocks_override=64, max_model_len=64,
                    max_num_batched_tokens=64, max_num_seqs=8,
                    skip_tokenizer_init=True)
        args.update(overrides)
        eng = LLMEngine(EA(**args).create_engine_config())
        return eng.engine_core.engine_core.executor.worker.model_runner

    rq = runner_of(quantization="gptq")
    rf = runner_of()
    lq, lf = rq.params["layers"], rf.params["layers"]
    # Served weights really are 4-bit payloads...
    assert lq["wq"].dtype == jnp.uint4
    # ...and their group-wise reconstruction is (near-)lossless against
    # the loader's fp dequant of the same GPTQ checkpoint.
    w = np2.asarray(lq["wq"], np2.float32)
    G = lq["wq_gscale"].shape[1]
    K = w.shape[1]
    wrec = (w.reshape(w.shape[0], G, K // G, -1) *
            np2.asarray(lq["wq_gscale"])[:, :, None, :] +
            np2.asarray(lq["wq_gmin"])[:, :, None, :]).reshape(w.shape)
    np2.testing.assert_allclose(wrec, np2.asarray(lf["wq"]), rtol=1e-4,
                                atol=1e-5)


def test_gptq_native_int4g_under_tp2(tmp_path_factory):
    """int4g group-wise serving under GSPMD TP=2: the group dim shards
    with the weight's input axis (the kernel path gates off; the XLA
    dequant-in-dot must agree with tp=1)."""
    torch.manual_seed(5)
    hf = HFLlama(LlamaConfig(**CFG))
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    packed_sd = {}
    for name, w in sd.items():
        if any(name.endswith(f"{t}.weight") for t in TARGETS):
            base = name[:-len(".weight")]
            packed, _ = quantize_gptq(w.astype(np.float32))
            for suffix, arr in packed.items():
                packed_sd[f"{base}.{suffix}"] = arr
        else:
            packed_sd[name] = w
    path = str(tmp_path_factory.mktemp("tiny_gptq_tp2"))
    save_file({k: np.ascontiguousarray(v) for k, v in packed_sd.items()},
              os.path.join(path, "model.safetensors"))
    cfg = dict(CFG, architectures=["LlamaForCausalLM"],
               model_type="llama")
    cfg["quantization_config"] = {
        "quant_method": "gptq", "bits": BITS, "group_size": GROUP,
        "desc_act": False, "sym": False}
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(cfg, f)
    single = _run(path, quantization="gptq")
    tp2 = _run(path, quantization="gptq", tensor_parallel_size=2)
    assert tp2 == single
