"""Prompt logprobs: per-prompt-position log-softmax scored during
prefill (reference: SamplingParams.prompt_logprobs +
gpu_model_runner._get_prompt_logprobs_dict), exact under chunked
prefill and prefix-caching bypass."""

import numpy as np
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

PROMPT = [3, 17, 92, 45, 8, 21, 33, 60]


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64, eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_plp")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def run_one(engine, prompt, **sp_kw):
    sp = SamplingParams(temperature=0.0, max_tokens=2, ignore_eos=True,
                        **sp_kw)
    engine.add_request("p", prompt, sp)
    final = None
    for _ in range(100):
        for out in engine.step():
            if out.request_id == "p":
                final = out
        if not engine.has_unfinished_requests():
            return final
    raise AssertionError("did not finish")


def hf_prompt_logprobs(hf, prompt):
    with torch.no_grad():
        logits = hf(torch.tensor([prompt])).logits[0]  # [L, V]
    lps = torch.log_softmax(logits.float(), dim=-1).numpy()
    # Entry i (i >= 1) = logprob of prompt[i] from position i-1.
    return [None] + [float(lps[i - 1, prompt[i]])
                     for i in range(1, len(prompt))]


def _check(out, hf, prompt, k=5):
    got = out.prompt_logprobs
    ref = hf_prompt_logprobs(hf, prompt)
    assert got is not None and len(got) == len(prompt)
    assert got[0] is None
    for i in range(1, len(prompt)):
        assert prompt[i] in got[i], f"entry {i} missing its own token"
        np.testing.assert_allclose(got[i][prompt[i]], ref[i], atol=1e-3,
                                   rtol=1e-3)
        # top-k alternatives present and no worse than the actual token.
        assert len(got[i]) >= min(k, 1)
        assert max(got[i].values()) >= got[i][prompt[i]] - 1e-6


def test_prompt_logprobs_match_hf(checkpoint):
    path, hf = checkpoint
    engine = make_engine(path)
    out = run_one(engine, PROMPT, prompt_logprobs=5)
    _check(out, hf, PROMPT)


def test_prompt_logprobs_exact_under_chunked_prefill(checkpoint):
    path, hf = checkpoint
    # 4-token budget chunks the 8-token prompt across steps.
    engine = make_engine(path, max_num_batched_tokens=4, max_num_seqs=2)
    out = run_one(engine, PROMPT, prompt_logprobs=5)
    _check(out, hf, PROMPT)


def test_prompt_logprobs_bypass_prefix_cache(checkpoint):
    """A cached prefix would skip the forward for those positions; the
    scheduler must recompute so every entry is scored."""
    path, hf = checkpoint
    engine = make_engine(path, enable_prefix_caching=True)
    # Warm the prefix cache with the same prompt (no plp).
    run_one(engine, PROMPT)
    out = run_one(engine, PROMPT, prompt_logprobs=5)
    _check(out, hf, PROMPT)


def test_prompt_logprobs_absent_when_not_requested(checkpoint):
    path, _ = checkpoint
    engine = make_engine(path)
    out = run_one(engine, PROMPT)
    assert out.prompt_logprobs is None


def test_prompt_logprobs_under_pipeline_parallelism(checkpoint):
    """plp scoring runs on the last stage's sub-mesh under PP."""
    path, hf = checkpoint
    engine = make_engine(path, pipeline_parallel_size=2)
    out = run_one(engine, PROMPT, prompt_logprobs=3)
    _check(out, hf, PROMPT, k=3)
