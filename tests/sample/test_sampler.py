"""Sampler unit tests (model: reference tests/v1/sample/)."""

import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.sample.metadata import (ExtendedSamplingMetadata,
                                                  SamplingMetadata)
from vllm_distributed_tpu.sample.sampler import (MAX_LOGPROBS,
                                                 apply_logits_processors,
                                                 compute_topk_logprobs,
                                                 sample_tokens,
                                                 sample_tokens_extended)


def md(R, temperature=1.0, top_k=0, top_p=1.0, min_p=0.0, seeds=None):
    return SamplingMetadata(
        temperature=jnp.full((R, ), temperature, jnp.float32),
        top_k=jnp.full((R, ), top_k, jnp.int32),
        top_p=jnp.full((R, ), top_p, jnp.float32),
        min_p=jnp.full((R, ), min_p, jnp.float32),
        seeds=jnp.asarray(seeds if seeds is not None else range(R),
                          jnp.int64),
    )


def ext_md(R, V, L=16, B=8, hist=None, prompt_len=None, total_len=None,
           presence=0.0, frequency=0.0, repetition=1.0, bias=None,
           base_fill=0.0):
    """Build an ExtendedSamplingMetadata; ``bias`` is a per-row list of
    (token, value) pairs."""
    hist_arr = np.zeros((R, L), np.int32)
    if hist is not None:
        for r, toks in enumerate(hist):
            hist_arr[r, :len(toks)] = toks
    bias_ids = np.full((R, B), V, np.int32)
    bias_vals = np.zeros((R, B), np.float32)
    if bias is not None:
        for r, entries in enumerate(bias):
            for j, (t, v) in enumerate(entries):
                bias_ids[r, j] = t
                bias_vals[r, j] = v
    return ExtendedSamplingMetadata(
        hist_tokens=jnp.asarray(hist_arr),
        prompt_len=jnp.asarray(
            prompt_len if prompt_len is not None else [0] * R, jnp.int32),
        total_len=jnp.asarray(
            total_len if total_len is not None else [0] * R, jnp.int32),
        presence_penalty=jnp.full((R, ), presence, jnp.float32),
        frequency_penalty=jnp.full((R, ), frequency, jnp.float32),
        repetition_penalty=jnp.full((R, ), repetition, jnp.float32),
        bias_ids=jnp.asarray(bias_ids),
        bias_vals=jnp.asarray(bias_vals),
        base_fill=jnp.full((R, ), base_fill, jnp.float32),
    )


def test_greedy_is_argmax():
    logits = jnp.asarray([[0.1, 3.0, -1.0, 2.0], [5.0, 0.0, 0.0, 6.0]])
    ids, lps = sample_tokens(logits, md(2, temperature=0.0))
    assert ids.tolist() == [1, 3]
    # Reported logprob is log_softmax at the chosen token.
    expect = np.log(np.exp(3.0) / np.exp(
        np.asarray([0.1, 3.0, -1.0, 2.0])).sum())
    np.testing.assert_allclose(float(lps[0]), expect, rtol=1e-5)


def test_top_k_one_equals_greedy():
    logits = jnp.asarray(np.random.default_rng(0).standard_normal((4, 16),
                                                                  ),
                         jnp.float32)
    ids_g, _ = sample_tokens(logits, md(4, temperature=0.0))
    ids_k, _ = sample_tokens(logits, md(4, temperature=1.0, top_k=1))
    assert ids_g.tolist() == ids_k.tolist()


def test_top_k_restricts_support():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.standard_normal((1, 32)), jnp.float32)
    top5 = set(np.asarray(logits)[0].argsort()[-5:].tolist())
    seen = set()
    for seed in range(200):
        ids, _ = sample_tokens(logits, md(1, temperature=2.0, top_k=5,
                                          seeds=[seed]))
        seen.add(int(ids[0]))
    assert seen <= top5
    assert len(seen) >= 3  # actually explores the allowed set


def test_top_p_restricts_support():
    # 90% mass on token 0, ~10% on token 1, rest tiny.
    logits = jnp.log(jnp.asarray([[0.9, 0.0999, 1e-4, 1e-6]]))
    seen = set()
    for seed in range(100):
        ids, _ = sample_tokens(logits, md(1, temperature=1.0, top_p=0.95,
                                          seeds=[seed]))
        seen.add(int(ids[0]))
    assert seen <= {0, 1}


def test_min_p_restricts_support():
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.1, 0.1]]))
    seen = set()
    for seed in range(100):
        ids, _ = sample_tokens(logits, md(1, temperature=1.0, min_p=0.5,
                                          seeds=[seed]))
        seen.add(int(ids[0]))
    # min_p=0.5 keeps tokens with p >= 0.5 * 0.5 = 0.25.
    assert seen <= {0, 1}


def test_seeded_determinism():
    logits = jnp.asarray(np.random.default_rng(2).standard_normal((3, 64)),
                         jnp.float32)
    a, _ = sample_tokens(logits, md(3, temperature=1.5, seeds=[7, 8, 9]))
    b, _ = sample_tokens(logits, md(3, temperature=1.5, seeds=[7, 8, 9]))
    c, _ = sample_tokens(logits, md(3, temperature=1.5, seeds=[10, 11, 12]))
    assert a.tolist() == b.tolist()
    assert a.tolist() != c.tolist()  # overwhelmingly likely


def test_sampling_roughly_matches_distribution():
    # Two tokens with 80/20 split; frequencies should track.
    logits = jnp.log(jnp.asarray([[0.8, 0.2]]))
    counts = [0, 0]
    for seed in range(400):
        ids, _ = sample_tokens(logits, md(1, temperature=1.0,
                                          seeds=[seed]))
        counts[int(ids[0])] += 1
    assert 240 <= counts[0] <= 380  # ~320 expected


def test_mixed_batch_greedy_and_random():
    logits = jnp.asarray([[10.0, 0.0, 0.0], [0.0, 0.0, 10.0]])
    m = SamplingMetadata(
        temperature=jnp.asarray([0.0, 1.0], jnp.float32),
        top_k=jnp.asarray([0, 1], jnp.int32),
        top_p=jnp.ones((2, ), jnp.float32),
        min_p=jnp.zeros((2, ), jnp.float32),
        seeds=jnp.asarray([0, 1], jnp.int64),
    )
    ids, _ = sample_tokens(logits, m)
    assert ids.tolist() == [0, 2]


def test_topk_logprobs():
    logits = jnp.asarray([[1.0, 3.0, 2.0, -1.0]])
    vals, ids = compute_topk_logprobs(logits, 2)
    assert ids[0].tolist() == [1, 2]
    total = np.exp(np.asarray(vals[0])).sum()
    assert total < 1.0


# ---------------------------------------------------------------------------
# Extended path: penalties / bias / masks (reference:
# vllm/v1/sample/ops/penalties.py, logits_processor.py)
# ---------------------------------------------------------------------------


def test_repetition_penalty_divides_positive_and_multiplies_negative():
    V = 8
    logits = jnp.asarray([[2.0, -2.0, 1.0, 0.5, 0, 0, 0, 0]], jnp.float32)
    # Tokens 0 (positive logit) and 1 (negative logit) appear in history.
    ext = ext_md(1, V, hist=[[0, 1]], prompt_len=[1], total_len=[2],
                 repetition=2.0)
    out = np.asarray(apply_logits_processors(logits, ext))
    np.testing.assert_allclose(out[0, 0], 1.0)   # 2.0 / 2
    np.testing.assert_allclose(out[0, 1], -4.0)  # -2.0 * 2
    np.testing.assert_allclose(out[0, 2], 1.0)   # untouched


def test_frequency_and_presence_penalties_count_output_only():
    V = 8
    logits = jnp.zeros((1, V), jnp.float32)
    # History: prompt [5, 5], output [5, 3] -> output counts: 5 -> 1, 3 -> 1.
    ext = ext_md(1, V, hist=[[5, 5, 5, 3]], prompt_len=[2], total_len=[4],
                 frequency=0.5, presence=0.25)
    out = np.asarray(apply_logits_processors(logits, ext))
    np.testing.assert_allclose(out[0, 5], -0.75)  # -0.5*1 - 0.25
    np.testing.assert_allclose(out[0, 3], -0.75)
    np.testing.assert_allclose(out[0, 0], 0.0)  # prompt-only would be 0 too


def test_history_padding_is_ignored():
    V = 8
    logits = jnp.zeros((1, V), jnp.float32)
    # total_len=0: nothing in history even though the buffer holds zeros
    # (token id 0 must NOT be penalized).
    ext = ext_md(1, V, hist=[[0, 0, 0]], prompt_len=[0], total_len=[0],
                 frequency=1.0, presence=1.0, repetition=5.0)
    out = np.asarray(apply_logits_processors(logits, ext))
    np.testing.assert_allclose(out, 0.0)


def test_logit_bias_scatter():
    V = 8
    logits = jnp.zeros((1, V), jnp.float32)
    ext = ext_md(1, V, bias=[[(3, 5.0), (4, -5.0)]])
    out = np.asarray(apply_logits_processors(logits, ext))
    np.testing.assert_allclose(out[0, 3], 5.0)
    np.testing.assert_allclose(out[0, 4], -5.0)
    np.testing.assert_allclose(out[0, 0], 0.0)


def test_allowed_token_ids_masks_everything_else():
    V = 8
    logits = jnp.asarray(
        np.random.default_rng(0).standard_normal((1, V)), jnp.float32)
    # base_fill=-inf with 0-valued entries at allowed ids {2, 6}.
    ext = ext_md(1, V, bias=[[(2, 0.0), (6, 0.0)]],
                 base_fill=float("-inf"))
    ids, _, _, _ = sample_tokens_extended(logits, md(1, temperature=0.0),
                                          ext)
    assert int(ids[0]) in (2, 6)


def test_extended_no_op_matches_plain():
    V = 32
    logits = jnp.asarray(
        np.random.default_rng(3).standard_normal((4, V)), jnp.float32)
    m = md(4, temperature=0.0)
    plain_ids, _ = sample_tokens(logits, m)
    ids, chosen, top_vals, top_ids = sample_tokens_extended(
        logits, m, ext_md(4, V))
    assert ids.tolist() == plain_ids.tolist()
    assert top_vals.shape == (4, MAX_LOGPROBS)
    # Chosen logprob appears at the right place in the topk list (greedy
    # choice = top-1).
    np.testing.assert_allclose(np.asarray(chosen), np.asarray(top_vals[:,
                                                                       0]),
                               rtol=1e-5)
    assert top_ids[:, 0].tolist() == ids.tolist()


# ---------------------------------------------------------------------------
# Rejection-sampling spec verification (sampler.spec_verify_rejection)
# ---------------------------------------------------------------------------

def _verify_md(R, S1, temperature):
    """Per-row metadata with [R*S1] per-position seeds (the layout the
    runner's dispatch builds)."""
    seeds = (np.arange(R, dtype=np.int64)[:, None] * 131 +
             7919 * np.arange(S1)[None, :])
    return SamplingMetadata(
        temperature=jnp.full((R, ), temperature, jnp.float32),
        top_k=jnp.zeros((R, ), jnp.int32),
        top_p=jnp.ones((R, ), jnp.float32),
        min_p=jnp.zeros((R, ), jnp.float32),
        seeds=jnp.asarray(seeds.reshape(-1)),
    )


def test_spec_verify_rejection_distribution_exact():
    """Emitted first tokens must be distributed exactly as the tempered
    target regardless of the draft distribution q: the accept test is
    min(1, p/q) and the rejection resample uses the exact residual
    max(p - q, 0)/Z."""
    from vllm_distributed_tpu.sample.sampler import spec_verify_rejection
    rng = np.random.default_rng(0)
    V, S, K, temp = 8, 1, 8, 1.0
    R = 20000  # rows = independent trials (distinct seeds per row)
    S1 = S + 1

    target_logits = rng.standard_normal(V).astype(np.float32) * 1.5
    q_logits = rng.standard_normal(V).astype(np.float32) * 1.5
    p = np.exp(target_logits) / np.exp(target_logits).sum()
    q = np.exp(q_logits) / np.exp(q_logits).sum()

    drafts = rng.choice(V, size=(R, S), p=q).astype(np.int32)
    q_ids = np.tile(np.arange(V, dtype=np.int32), (R, S, 1))
    q_probs = np.tile(q.astype(np.float32), (R, S, 1))
    logits = np.tile(target_logits, (R, S1, 1))

    accept, residual, _bonus, _lpc, _lpb = spec_verify_rejection(
        jnp.asarray(logits), jnp.asarray(drafts), jnp.asarray(q_ids),
        jnp.asarray(q_probs), _verify_md(R, S1, temp))
    accept = np.asarray(accept)
    residual = np.asarray(residual)

    emitted = np.where(accept[:, 0], drafts[:, 0], residual[:, 0])
    freq = np.bincount(emitted, minlength=V) / R
    # Exactness: empirical distribution matches p within Monte-Carlo
    # noise (3 sigma ~ 3*sqrt(p(1-p)/R) < 0.011 for any p).
    np.testing.assert_allclose(freq, p, atol=0.015)
    # Acceptance must beat the prefix-match rate sum(p*q) when q is
    # closer to p than independence: the expected accept prob is
    # sum(min(p, q)) > sum(p*q).
    accept_rate = accept[:, 0].mean()
    np.testing.assert_allclose(accept_rate, np.minimum(p, q).sum(),
                               atol=0.02)
    assert accept_rate > float((p * q).sum()) + 0.05


def test_spec_verify_greedy_rows_prefix_match():
    """temperature = 0 rows accept iff the target argmax equals the
    draft and emit the argmax on rejection."""
    from vllm_distributed_tpu.sample.sampler import spec_verify_rejection
    V, S, K = 8, 2, 4
    R, S1 = 2, S + 1
    logits = np.zeros((R, S1, V), np.float32)
    logits[:, :, 5] = 3.0  # argmax = 5 at every position
    drafts = np.asarray([[5, 5], [5, 2]], np.int32)
    q_ids = np.zeros((R, S, K), np.int32)
    q_ids[..., 0] = drafts
    q_probs = np.zeros((R, S, K), np.float32)
    q_probs[..., 0] = 1.0
    accept, residual, bonus, _lpc, _lpb = spec_verify_rejection(
        jnp.asarray(logits), jnp.asarray(drafts), jnp.asarray(q_ids),
        jnp.asarray(q_probs), _verify_md(R, S1, 0.0))
    assert np.asarray(accept).tolist() == [[True, True], [True, False]]
    assert int(np.asarray(bonus)[0]) == 5
    assert int(np.asarray(residual)[1, 1]) == 5


def test_spec_verify_respects_topk_topp_truncation():
    """A request's top-k/top-p/min-p truncation applies to the TARGET
    in spec verification (ADVICE r5 high): a draft outside the
    truncated support must never be accepted, and residual/bonus
    emits must stay inside the support — matching the non-spec
    sampler's distribution."""
    import dataclasses

    from vllm_distributed_tpu.sample.sampler import spec_verify_rejection
    rng = np.random.default_rng(2)
    V, S, K, temp = 16, 1, 16, 1.0
    R, S1 = 4000, S + 1

    target = rng.standard_normal(V).astype(np.float32)
    top2 = set(np.argsort(target)[-2:].tolist())
    # Drafter q: uniform over the WHOLE vocab — mostly outside the
    # top_k=2 truncated target support.
    q = np.full(V, 1.0 / V, np.float32)
    drafts = rng.choice(V, size=(R, S), p=q).astype(np.int32)
    q_ids = np.tile(np.arange(V, dtype=np.int32), (R, S, 1))
    q_probs = np.tile(q, (R, S, 1))
    logits = np.tile(target, (R, S1, 1))

    md = dataclasses.replace(
        _verify_md(R, S1, temp),
        top_k=jnp.full((R, ), 2, jnp.int32))
    accept, residual, bonus, _lpc, _lpb = spec_verify_rejection(
        jnp.asarray(logits), jnp.asarray(drafts), jnp.asarray(q_ids),
        jnp.asarray(q_probs), md)
    accept = np.asarray(accept)
    residual = np.asarray(residual)
    bonus = np.asarray(bonus)

    emitted = np.where(accept[:, 0], drafts[:, 0], residual[:, 0])
    assert set(np.unique(emitted).tolist()) <= top2, \
        "spec decode emitted a token outside the top-k support"
    # Bonus tokens (rows whose draft was accepted) obey it too.
    assert set(np.unique(bonus[accept[:, 0]]).tolist()) <= top2
    # Accepted drafts are necessarily in-support.
    assert set(np.unique(drafts[accept]).tolist()) <= top2
    # The emitted distribution matches the truncated renormalized p.
    p = np.exp(target) / np.exp(target).sum()
    p_trunc = np.where(np.isin(np.arange(V), list(top2)), p, 0.0)
    p_trunc /= p_trunc.sum()
    freq = np.bincount(emitted, minlength=V) / R
    np.testing.assert_allclose(freq, p_trunc, atol=0.03)


def test_spec_verify_no_draft_rows_emit_plain_sample():
    """Rows with no drafts (all -1, zero q) reject at position 0 and the
    residual IS a plain tempered-target sample (q = 0 -> residual = p)."""
    from vllm_distributed_tpu.sample.sampler import spec_verify_rejection
    rng = np.random.default_rng(1)
    V, S, K = 16, 2, 4
    R, S1 = 8000, S + 1
    target = rng.standard_normal(V).astype(np.float32)
    p = np.exp(target) / np.exp(target).sum()
    logits = np.tile(target, (R, S1, 1))
    drafts = np.full((R, S), -1, np.int32)
    q_ids = np.zeros((R, S, K), np.int32)
    q_probs = np.zeros((R, S, K), np.float32)
    accept, residual, _b, _lpc, _lpb = spec_verify_rejection(
        jnp.asarray(logits), jnp.asarray(drafts), jnp.asarray(q_ids),
        jnp.asarray(q_probs), _verify_md(R, S1, 1.0))
    assert not np.asarray(accept).any()
    freq = np.bincount(np.asarray(residual)[:, 0], minlength=V) / R
    np.testing.assert_allclose(freq, p, atol=0.02)
