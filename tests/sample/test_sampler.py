"""Sampler unit tests (model: reference tests/v1/sample/)."""

import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.sample.metadata import SamplingMetadata
from vllm_distributed_tpu.sample.sampler import (compute_topk_logprobs,
                                                 sample_tokens)


def md(R, temperature=1.0, top_k=0, top_p=1.0, min_p=0.0, seeds=None):
    return SamplingMetadata(
        temperature=jnp.full((R, ), temperature, jnp.float32),
        top_k=jnp.full((R, ), top_k, jnp.int32),
        top_p=jnp.full((R, ), top_p, jnp.float32),
        min_p=jnp.full((R, ), min_p, jnp.float32),
        seeds=jnp.asarray(seeds if seeds is not None else range(R),
                          jnp.int64),
    )


def test_greedy_is_argmax():
    logits = jnp.asarray([[0.1, 3.0, -1.0, 2.0], [5.0, 0.0, 0.0, 6.0]])
    ids, lps = sample_tokens(logits, md(2, temperature=0.0))
    assert ids.tolist() == [1, 3]
    # Reported logprob is log_softmax at the chosen token.
    expect = np.log(np.exp(3.0) / np.exp(
        np.asarray([0.1, 3.0, -1.0, 2.0])).sum())
    np.testing.assert_allclose(float(lps[0]), expect, rtol=1e-5)


def test_top_k_one_equals_greedy():
    logits = jnp.asarray(np.random.default_rng(0).standard_normal((4, 16),
                                                                  ),
                         jnp.float32)
    ids_g, _ = sample_tokens(logits, md(4, temperature=0.0))
    ids_k, _ = sample_tokens(logits, md(4, temperature=1.0, top_k=1))
    assert ids_g.tolist() == ids_k.tolist()


def test_top_k_restricts_support():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.standard_normal((1, 32)), jnp.float32)
    top5 = set(np.asarray(logits)[0].argsort()[-5:].tolist())
    seen = set()
    for seed in range(200):
        ids, _ = sample_tokens(logits, md(1, temperature=2.0, top_k=5,
                                          seeds=[seed]))
        seen.add(int(ids[0]))
    assert seen <= top5
    assert len(seen) >= 3  # actually explores the allowed set


def test_top_p_restricts_support():
    # 90% mass on token 0, ~10% on token 1, rest tiny.
    logits = jnp.log(jnp.asarray([[0.9, 0.0999, 1e-4, 1e-6]]))
    seen = set()
    for seed in range(100):
        ids, _ = sample_tokens(logits, md(1, temperature=1.0, top_p=0.95,
                                          seeds=[seed]))
        seen.add(int(ids[0]))
    assert seen <= {0, 1}


def test_min_p_restricts_support():
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.1, 0.1]]))
    seen = set()
    for seed in range(100):
        ids, _ = sample_tokens(logits, md(1, temperature=1.0, min_p=0.5,
                                          seeds=[seed]))
        seen.add(int(ids[0]))
    # min_p=0.5 keeps tokens with p >= 0.5 * 0.5 = 0.25.
    assert seen <= {0, 1}


def test_seeded_determinism():
    logits = jnp.asarray(np.random.default_rng(2).standard_normal((3, 64)),
                         jnp.float32)
    a, _ = sample_tokens(logits, md(3, temperature=1.5, seeds=[7, 8, 9]))
    b, _ = sample_tokens(logits, md(3, temperature=1.5, seeds=[7, 8, 9]))
    c, _ = sample_tokens(logits, md(3, temperature=1.5, seeds=[10, 11, 12]))
    assert a.tolist() == b.tolist()
    assert a.tolist() != c.tolist()  # overwhelmingly likely


def test_sampling_roughly_matches_distribution():
    # Two tokens with 80/20 split; frequencies should track.
    logits = jnp.log(jnp.asarray([[0.8, 0.2]]))
    counts = [0, 0]
    for seed in range(400):
        ids, _ = sample_tokens(logits, md(1, temperature=1.0,
                                          seeds=[seed]))
        counts[int(ids[0])] += 1
    assert 240 <= counts[0] <= 380  # ~320 expected


def test_mixed_batch_greedy_and_random():
    logits = jnp.asarray([[10.0, 0.0, 0.0], [0.0, 0.0, 10.0]])
    m = SamplingMetadata(
        temperature=jnp.asarray([0.0, 1.0], jnp.float32),
        top_k=jnp.asarray([0, 1], jnp.int32),
        top_p=jnp.ones((2, ), jnp.float32),
        min_p=jnp.zeros((2, ), jnp.float32),
        seeds=jnp.asarray([0, 1], jnp.int64),
    )
    ids, _ = sample_tokens(logits, m)
    assert ids.tolist() == [0, 2]


def test_topk_logprobs():
    logits = jnp.asarray([[1.0, 3.0, 2.0, -1.0]])
    vals, ids = compute_topk_logprobs(logits, 2)
    assert ids[0].tolist() == [1, 2]
    total = np.exp(np.asarray(vals[0])).sum()
    assert total < 1.0
