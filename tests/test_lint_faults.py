"""CI guard: every FAULT_POINTS name stays exercised by a test.

Runs scripts/lint_faults.py over the real registry + tests/ tree (so a
new fault point cannot land without a drill) and unit-tests the
linter's failure modes on synthetic trees."""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "lint_faults.py"

_REGISTRY = '''\
FAULT_POINTS = (
    "engine.die",  # comment survives the parse
    "pull.delay",
)
'''


def _run(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run([sys.executable, str(SCRIPT), *args],
                          capture_output=True, text=True, timeout=60)


def _arm(name: str, tail: str = "") -> str:
    """Synthetic ``fi.inject("<name>")`` line, assembled so THIS file
    never contains a contiguous armed literal — the real-tree run in
    test_package_fault_points_are_exercised scans tests/ including this
    wrapper, and the fixture names must not read as typo'd drills."""
    return "fi.inject(" + f'"{name}"{tail})\n'


def _tree(tmp_path, registry: str, tests: dict[str, str]):
    reg = tmp_path / "fault_injection.py"
    reg.write_text(registry)
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    for name, text in tests.items():
        (tests_dir / name).write_text(text)
    return reg, tests_dir


def test_package_fault_points_are_exercised():
    res = _run()
    assert res.returncode == 0, (
        f"fault-point drill coverage drifted:\n{res.stderr}")


def test_unexercised_point_is_caught(tmp_path):
    reg, tests = _tree(tmp_path, _REGISTRY, {
        "test_a.py": _arm("engine.die", ", max_fires=1")})
    res = _run("--registry", str(reg), "--tests", str(tests))
    assert res.returncode == 1
    assert "pull.delay" in res.stderr
    assert "untested failure mode" in res.stderr


def test_single_quoted_reference_counts(tmp_path):
    reg, tests = _tree(tmp_path, _REGISTRY, {
        "test_a.py": "fi.inject(" + "'engine.die')\n",
        "test_b.py": "assert counters()['pull.delay'] == 1\n"})
    res = _run("--registry", str(reg), "--tests", str(tests))
    assert res.returncode == 0, res.stderr


def test_typoed_drill_is_caught(tmp_path):
    reg, tests = _tree(tmp_path, _REGISTRY, {
        "test_a.py": (_arm("engine.die") + _arm("pull.delay")
                      + _arm("engine.dye"))})
    res = _run("--registry", str(reg), "--tests", str(tests))
    assert res.returncode == 1
    assert "engine.dye" in res.stderr
    assert "typo'd drill" in res.stderr


def test_dotted_strings_outside_injection_api_are_not_typos(tmp_path):
    """Only names armed via the injection API count as drill
    references for the typo check — a dotted module path in an import
    or monkeypatch target must not trip it."""
    reg, tests = _tree(tmp_path, _REGISTRY, {
        "test_a.py": (_arm("engine.die") + _arm("pull.delay")
                      + 'monkeypatch.setattr("pkg.module", None)\n')})
    res = _run("--registry", str(reg), "--tests", str(tests))
    assert res.returncode == 0, res.stderr


def test_missing_registry_is_a_usage_error(tmp_path):
    res = _run("--registry", str(tmp_path / "nope.py"),
               "--tests", str(tmp_path))
    assert res.returncode == 2
