"""CI guard: wall-clock deadline arithmetic stays banned.

Runs scripts/lint_deadlines.py over the framework package (the tier-1
mechanical check for the monotonic-clock migration) and unit-tests the
linter's flag/allowlist behavior on synthetic trees."""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "lint_deadlines.py"


def _run(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run([sys.executable, str(SCRIPT), *args],
                          capture_output=True, text=True, timeout=60)


def test_package_has_no_wallclock_deadlines():
    res = _run()
    assert res.returncode == 0, (
        f"wall-clock deadline arithmetic crept back in:\n{res.stderr}")


def test_linter_flags_deadline_arithmetic(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import time\n"
                   "deadline = time.time() + 30.0\n")
    res = _run("--root", str(tmp_path))
    assert res.returncode == 1
    assert "bad.py:2" in res.stderr


def test_linter_flags_default_factory(tmp_path):
    bad = tmp_path / "factory.py"
    bad.write_text("import time\n"
                   "from dataclasses import dataclass, field\n"
                   "@dataclass\n"
                   "class T:\n"
                   "    expires: float = field(default_factory=time.time)\n")
    res = _run("--root", str(tmp_path))
    assert res.returncode == 1
    assert "factory.py:5" in res.stderr


def test_marker_allowlists_timestamp_uses(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text("import time\n"
                  "created = int(time.time())  # wallclock-ok\n"
                  "# wallclock-ok: epoch stat for the API body\n"
                  "arrival = time.time()\n")
    res = _run("--root", str(tmp_path))
    assert res.returncode == 0, res.stderr


def test_missing_root_is_a_usage_error(tmp_path):
    res = _run("--root", str(tmp_path / "nope"))
    assert res.returncode == 2
