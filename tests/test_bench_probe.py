"""bench.py accelerator probe: warning-only output is a liveness
verdict, not a timeout.

BENCH_r05.json's probe_log showed the failure mode this guards: the
experimental-platform plugin prints its warning banner within seconds,
then hangs jax.devices() forever — and the old probe burned 2 x 120 s
attempt timeouts (the whole 300 s budget) before falling back to CPU.
The streamed probe must conclude 'hung' within the liveness window and
skip the remaining attempts entirely."""

import importlib.util
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture()
def bench(monkeypatch):
    spec = importlib.util.spec_from_file_location("_bench_under_test",
                                                  REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # Fast knobs: the real budget/timeout would stall the test tier.
    monkeypatch.setattr(mod, "_PROBE_BUDGET", 30.0)
    monkeypatch.setattr(mod, "_PROBE_LIVENESS", 2.0)
    mod._PROBE_LOG.clear()
    return mod


def test_warning_only_classifier(bench):
    warn = ("WARNING:2026-08-01 04:00:09,107:jax._src.xla_bridge:905: "
            "Platform 'axon' is experimental and not all JAX "
            "functionality may be correctly supported!\n")
    assert bench._stderr_warning_only(warn)
    assert bench._stderr_warning_only(warn + warn)
    assert not bench._stderr_warning_only("")
    assert not bench._stderr_warning_only(
        warn + "Traceback (most recent call last):\n  boom\n")
    assert not bench._stderr_warning_only("RuntimeError: Unavailable\n")


def test_hung_experimental_platform_falls_back_in_seconds(bench,
                                                          monkeypatch):
    """A probe that prints only the experimental-platform warning and
    then hangs must be classified 'hung-warning' inside the liveness
    window, confirmed ONCE with an extended window (a healthy tunnelled
    init can be warning-then-silent for a while), then abandoned — the
    old behavior burned every full attempt timeout on identical
    hangs."""
    monkeypatch.setattr(
        bench, "_PROBE", (
            "import sys, time; "
            "sys.stderr.write(\"WARNING: Platform 'axon' is "
            "experimental and not all JAX functionality may be "
            "correctly supported!\\n\"); "
            "sys.stderr.flush(); time.sleep(600)"))
    t0 = time.monotonic()
    assert bench._probe_accelerator() is False
    elapsed = time.monotonic() - t0
    # One liveness window + one 4x confirmation window — not one (let
    # alone two) full attempt timeouts.
    assert elapsed < 25, f"fallback took {elapsed:.1f}s"
    hung = [line for line in bench._PROBE_LOG if "hung-warning" in line]
    assert len(hung) == 2  # initial verdict + extended confirmation
    assert sum("attempt" in line for line in bench._PROBE_LOG) == 2


def test_repeating_warning_banner_is_hung_not_timeout(bench,
                                                      monkeypatch):
    """BENCH_r05 regression: a hung plugin that RE-PRINTS its
    experimental banner every ~0.5 s keeps stderr growing forever, so a
    quiet-clock based on raw growth never expires and the old probe
    burned the full attempt timeout ('timeout' verdict) twice.  The
    liveness clock must only count novel (non-warning) content: the
    repeating banner probe is classified 'hung-warning' inside the
    liveness window and abandoned after the single confirmation
    retry."""
    monkeypatch.setattr(
        bench, "_PROBE", (
            "import sys, time\n"
            "while True:\n"
            "    t = time.strftime('%H:%M:%S')\n"
            "    sys.stderr.write('WARNING:' + t + ':jax._src.xla_bridge"
            ":905: Platform \\'axon\\' is experimental and not all JAX "
            "functionality may be correctly supported!\\n')\n"
            "    sys.stderr.flush()\n"
            "    time.sleep(0.5)\n"))
    t0 = time.monotonic()
    assert bench._probe_accelerator() is False
    elapsed = time.monotonic() - t0
    assert elapsed < 25, f"fallback took {elapsed:.1f}s"
    hung = [line for line in bench._PROBE_LOG if "hung-warning" in line]
    assert len(hung) == 2  # initial verdict + extended confirmation
    assert not any("timeout" in line for line in bench._PROBE_LOG)


def test_slow_but_healthy_init_survives_first_hung_verdict(bench,
                                                           monkeypatch):
    """A platform that prints the warning, stays silent past the first
    liveness window, but completes within the extended confirmation
    window must still be detected as an accelerator (the confirmation
    retry exists exactly for slow tunnelled inits)."""
    monkeypatch.setattr(
        bench, "_PROBE", (
            "import sys, time; "
            "sys.stderr.write(\"WARNING: Platform 'axon' is "
            "experimental\\n\"); sys.stderr.flush(); "
            "time.sleep(5); "
            "print('PLATFORM=axon KIND=tpu-v5e INIT_S=5.0')"))
    # First attempt's 2s window fires 'hung-warning'; the 8s
    # confirmation attempt lets the 5s init finish.
    assert bench._probe_accelerator() is True
    assert any("accel" in line for line in bench._PROBE_LOG)


def test_clean_cpu_probe_returns_false_fast(bench, monkeypatch):
    monkeypatch.setattr(
        bench, "_PROBE",
        "print('PLATFORM=cpu KIND=cpu INIT_S=0.1')")
    t0 = time.monotonic()
    assert bench._probe_accelerator() is False
    assert time.monotonic() - t0 < 10
    assert any("cpu" in line for line in bench._PROBE_LOG)


def test_accelerator_probe_returns_true(bench, monkeypatch):
    monkeypatch.setattr(
        bench, "_PROBE",
        "import sys; "
        "sys.stderr.write(\"WARNING: Platform 'axon' is experimental\\n\"); "
        "print('PLATFORM=axon KIND=tpu-v5e INIT_S=1.0')")
    assert bench._probe_accelerator() is True


def test_erroring_probe_retries_then_fails(bench, monkeypatch):
    """A crashing probe (rc != 0, non-warning stderr) keeps the old
    retry-with-backoff behavior."""
    monkeypatch.setattr(
        bench, "_PROBE",
        "import sys; sys.exit('RuntimeError: Unavailable')")
    monkeypatch.setattr(time, "sleep", lambda s: None)
    assert bench._probe_accelerator() is False
    assert sum("fail" in line for line in bench._PROBE_LOG) >= 2
