"""CI guard: the lifecycle event vocabulary stays documented.

Runs scripts/lint_events.py over the real package + README (tier-1
mechanical check: every EVENT_REGISTRY entry has a README row between
the lint-events markers and every record site uses a constant) and
unit-tests the linter's failure modes on synthetic trees."""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "lint_events.py"

GOOD_EVENTS = '''\
QUEUED = "queued"
FINISHED = "finished"

EVENT_REGISTRY = {
    QUEUED: "request admitted to the scheduler queue",
    FINISHED: "request finished",
}

DETAIL_KEY = "tr"
'''

GOOD_README = """\
# pkg

<!-- lint-events:begin -->
| event | meaning |
|---|---|
| `queued` | admitted |
| `finished` | done |
<!-- lint-events:end -->
"""


def _run(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run([sys.executable, str(SCRIPT), *args],
                          capture_output=True, text=True, timeout=60)


def _tree(tmp_path, events: str = GOOD_EVENTS,
          readme: str = GOOD_README, extra: str = ""):
    pkg = tmp_path / "pkg"
    (pkg / "metrics").mkdir(parents=True, exist_ok=True)
    (pkg / "metrics" / "events.py").write_text(events)
    if extra:
        (pkg / "recorder_site.py").write_text(extra)
    readme_path = tmp_path / "README.md"
    readme_path.write_text(readme)
    return pkg, readme_path


def test_package_events_are_documented():
    res = _run()
    assert res.returncode == 0, (
        f"vdt: event documentation drifted:\n{res.stderr}")


def test_clean_tree_passes(tmp_path):
    pkg, readme = _tree(tmp_path)
    res = _run("--package", str(pkg), "--readme", str(readme))
    assert res.returncode == 0, res.stderr


def test_unregistered_constant_is_caught(tmp_path):
    events = GOOD_EVENTS.replace(
        'FINISHED = "finished"',
        'FINISHED = "finished"\nSNEAKY = "sneaky"')
    pkg, readme = _tree(tmp_path, events=events)
    res = _run("--package", str(pkg), "--readme", str(readme))
    assert res.returncode == 1
    assert "SNEAKY" in res.stderr
    assert "missing from EVENT_REGISTRY" in res.stderr


def test_constants_below_registry_are_not_vocabulary(tmp_path):
    # DETAIL_KEY sits below the registry literal in the fixture: detail
    # keys and thresholds must not be mistaken for event names.
    pkg, readme = _tree(tmp_path)
    res = _run("--package", str(pkg), "--readme", str(readme))
    assert res.returncode == 0, res.stderr
    assert "DETAIL_KEY" not in res.stderr


def test_missing_readme_row_is_caught(tmp_path):
    readme = GOOD_README.replace("| `finished` | done |\n", "")
    pkg, readme_path = _tree(tmp_path, readme=readme)
    res = _run("--package", str(pkg), "--readme", str(readme_path))
    assert res.returncode == 1
    assert "finished" in res.stderr
    assert "missing from the README events table" in res.stderr


def test_orphaned_readme_row_is_caught(tmp_path):
    readme = GOOD_README.replace(
        "| `finished` | done |", "| `finished` | done |\n"
        "| `ghost_event` | no constant declares me |")
    pkg, readme_path = _tree(tmp_path, readme=readme)
    res = _run("--package", str(pkg), "--readme", str(readme_path))
    assert res.returncode == 1
    assert "ghost_event" in res.stderr
    assert "orphaned row" in res.stderr


def test_missing_markers_is_caught(tmp_path):
    pkg, readme_path = _tree(
        tmp_path, readme="# pkg\n\n| `queued` | x |\n")
    res = _run("--package", str(pkg), "--readme", str(readme_path))
    assert res.returncode == 1
    assert "lint-events:begin" in res.stderr


def test_literal_record_site_is_caught(tmp_path):
    pkg, readme_path = _tree(
        tmp_path,
        extra='def f(r, rid):\n    r.record(rid, "queued", None)\n')
    res = _run("--package", str(pkg), "--readme", str(readme_path))
    assert res.returncode == 1
    assert "raw string literal" in res.stderr
    # ...while a constant reference at the same site is fine.
    pkg, readme_path = _tree(
        tmp_path,
        extra='def f(r, rid, ev):\n'
              '    r.record(rid, ev.QUEUED, None)\n')
    res = _run("--package", str(pkg), "--readme", str(readme_path))
    assert res.returncode == 0, res.stderr
