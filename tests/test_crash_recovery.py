"""Crash recovery: restart supervisor + in-flight request replay.

Deterministic drills over the named fault points: ``engine_core.die``
mid-decode must resume token-identically after a supervisor respawn,
``restart.storm`` must burn the restart budget down to the terminal
EngineDeadError circuit breaker, and ``core_proc.spawn_fail`` must make
respawns themselves count against the budget."""

import asyncio
import time

import pytest

from vllm_distributed_tpu.engine.core_client import (EngineDeadError,
                                                     RestartSupervisor)
from vllm_distributed_tpu.request import (EngineCoreRequest,
                                          continuation_request)
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import fault_injection as fi

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_faults():
    fi.clear()
    yield
    fi.clear()


# ---------------------------------------------------------------------------
# RestartSupervisor unit
# ---------------------------------------------------------------------------

def test_supervisor_backoff_and_budget():
    sup = RestartSupervisor(max_attempts=3, window_s=60.0,
                            backoff_base_s=0.5, backoff_max_s=30.0)
    assert sup.next_delay() == 0.5
    assert sup.next_delay() == 1.0
    assert sup.next_delay() == 2.0
    assert sup.next_delay() is None  # budget burnt -> circuit breaker
    assert sup.exhausted


def test_supervisor_window_slides():
    sup = RestartSupervisor(max_attempts=1, window_s=0.05,
                            backoff_base_s=0.0, backoff_max_s=0.0)
    assert sup.next_delay() == 0.0
    assert sup.next_delay() is None
    time.sleep(0.06)  # the attempt ages out of the window
    assert not sup.exhausted
    assert sup.next_delay() == 0.0


def test_supervisor_disabled_refuses_immediately():
    sup = RestartSupervisor(max_attempts=0, window_s=60.0,
                            backoff_base_s=0.5, backoff_max_s=30.0)
    assert sup.next_delay() is None


def test_supervisor_backoff_is_capped():
    sup = RestartSupervisor(max_attempts=10, window_s=600.0,
                            backoff_base_s=1.0, backoff_max_s=4.0)
    delays = [sup.next_delay() for _ in range(5)]
    assert delays == [1.0, 2.0, 4.0, 4.0, 4.0]


# ---------------------------------------------------------------------------
# continuation_request unit
# ---------------------------------------------------------------------------

def _req(prompt, max_tokens=16, **sp):
    return EngineCoreRequest(
        request_id="r0", prompt_token_ids=list(prompt),
        sampling_params=SamplingParams(temperature=0.0,
                                       max_tokens=max_tokens, **sp))


def test_continuation_absorbs_generated_tokens():
    orig = _req([1, 2, 3], max_tokens=10)
    cont = continuation_request(orig, [7, 8])
    assert cont.prompt_token_ids == [1, 2, 3, 7, 8]
    assert cont.sampling_params.max_tokens == 8
    # The original is untouched (it may be journaled again).
    assert orig.prompt_token_ids == [1, 2, 3]
    assert orig.sampling_params.max_tokens == 10


def test_continuation_with_no_progress_is_the_original():
    orig = _req([1, 2, 3], max_tokens=10)
    cont = continuation_request(orig, [])
    assert cont.prompt_token_ids == [1, 2, 3]
    assert cont.sampling_params.max_tokens == 10


def test_continuation_keeps_at_least_one_token():
    orig = _req([1, 2], max_tokens=3)
    cont = continuation_request(orig, [5, 6, 7])
    assert cont.sampling_params.max_tokens == 1


def test_continuation_shrinks_min_tokens():
    orig = _req([1, 2], max_tokens=8, min_tokens=4)
    cont = continuation_request(orig, [5, 6])
    assert cont.sampling_params.min_tokens == 2


# ---------------------------------------------------------------------------
# Engine-level: die mid-decode -> respawn -> token-identical resume
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    import torch
    from transformers import LlamaConfig
    from transformers import LlamaForCausalLM as HFLlama
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64, eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_recovery")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


PROMPT = [3, 17, 92, 45, 8, 21, 33, 64, 90]


def _make_async_engine(checkpoint, **overrides):
    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    args = dict(model=checkpoint, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True,
                restart_backoff_base_s=0.01, restart_backoff_max_s=0.05)
    args.update(overrides)
    return AsyncLLM(EngineArgs(**args).create_engine_config(),
                    load_tokenizer=False)


async def _collect(engine, request_id, max_tokens=24, die_after=None):
    """Stream one greedy request; optionally arm engine_core.die after
    the first output arrives (i.e. mid-decode)."""
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                        ignore_eos=True)
    final = None
    got_first = False
    async for out in engine.generate(PROMPT, sp, request_id=request_id):
        if not got_first:
            got_first = True
            if die_after:
                fi.inject("engine_core.die", max_fires=1)
        final = out
    assert final is not None and final.finished
    return final.outputs[0].token_ids


def test_die_mid_decode_resumes_token_identical(checkpoint):
    """Acceptance: kill the core mid-decode; the supervisor respawns it,
    the journaled request replays as a continuation prefill, and the
    greedy stream finishes token-identical to an uninterrupted run."""
    baseline_engine = _make_async_engine(checkpoint)
    try:
        baseline = asyncio.run(asyncio.wait_for(
            _collect(baseline_engine, "base-0"), timeout=120.0))
    finally:
        baseline_engine.shutdown()
    assert len(baseline) == 24

    engine = _make_async_engine(checkpoint)
    try:
        resumed = asyncio.run(asyncio.wait_for(
            _collect(engine, "die-0", die_after=True), timeout=180.0))
        assert resumed == baseline, (
            "resumed stream diverged from the uninterrupted run")
        assert not engine.errored
        stats = engine.output_processor.stats
        assert stats.num_requests_replayed >= 1
        assert stats.num_engine_deaths >= 1
        assert fi.counters().get("engine_core.die", 0) >= 1
        # The engine keeps serving after recovery.
        again = asyncio.run(asyncio.wait_for(
            _collect(engine, "after-0"), timeout=120.0))
        assert again == baseline
    finally:
        engine.shutdown()


def test_restart_storm_circuit_breaks(checkpoint):
    """Acceptance: every respawned core dies again immediately
    (restart.storm); after restart_max_attempts the supervisor
    circuit-breaks and pending requests surface EngineDeadError."""
    engine = _make_async_engine(checkpoint, restart_max_attempts=2)

    async def run():
        sp = SamplingParams(temperature=0.0, max_tokens=32,
                            ignore_eos=True)
        got_first = False
        async for _ in engine.generate(PROMPT, sp, request_id="storm-0"):
            if not got_first:
                got_first = True
                fi.inject("restart.storm")  # every restart re-dies
                fi.inject("engine_core.die", max_fires=1)

    try:
        with pytest.raises(EngineDeadError):
            asyncio.run(asyncio.wait_for(run(), timeout=180.0))
        assert engine.errored
        # The budget granted exactly restart_max_attempts respawns.
        assert fi.counters().get("restart.storm", 0) == 2
    finally:
        engine.shutdown()


def test_spawn_fail_burns_restart_budget(checkpoint):
    """core_proc.spawn_fail: the respawn itself fails, consuming the
    budget without ever producing a live core -> terminal death."""
    engine = _make_async_engine(checkpoint, restart_max_attempts=2)

    async def run():
        sp = SamplingParams(temperature=0.0, max_tokens=32,
                            ignore_eos=True)
        got_first = False
        async for _ in engine.generate(PROMPT, sp, request_id="sf-0"):
            if not got_first:
                got_first = True
                fi.inject("core_proc.spawn_fail")
                fi.inject("engine_core.die", max_fires=1)

    try:
        with pytest.raises(EngineDeadError):
            asyncio.run(asyncio.wait_for(run(), timeout=180.0))
        assert engine.errored
        assert fi.counters().get("core_proc.spawn_fail", 0) == 2
    finally:
        engine.shutdown()
