#!/usr/bin/env python3
"""Ban undocumented (and orphaned) lifecycle event names.

The trace plane renders every ``EventRecorder.record`` event name as a
span type in Perfetto exports and /debug timelines, so an event name
that drifts undocumented is an unreadable trace lane. The contract
(lint_metrics applied to the event vocabulary):

* **vocabulary** — every module-level ``NAME = "value"`` constant
  defined in ``metrics/events.py`` ABOVE the ``EVENT_REGISTRY`` literal
  (the constants below it — detail keys, thresholds — are not event
  names).
* **registered** — each vocabulary constant must appear as a key of
  ``EVENT_REGISTRY`` with a non-empty one-line doc.
* **recorded by constant** — ``.record(...)`` / ``_record_event(...)``
  call sites under the package tree must pass the event as a constant
  reference, never a raw string literal (a literal bypasses the
  registry and this linter).
* **documented** — each registry event name must appear as a backticked
  token between the ``<!-- lint-events:begin/end -->`` markers in the
  README (the events table); a backticked name in that section that no
  registry entry declares is an orphaned row.

Usage::

    python scripts/lint_events.py [--package DIR] [--readme FILE]

Exit 0 when clean; exit 1 listing violations otherwise.
"""

import argparse
import re
import sys
from pathlib import Path

# Module-level event constant: NAME = "value" at column 0.
CONSTANT_RE = re.compile(
    r'^([A-Z][A-Z0-9_]*)\s*=\s*"([a-z][a-z0-9_]*)"', re.MULTILINE)
# Registry entry: CONSTANT: "doc..." (docs are single-line literals).
REGISTRY_ENTRY_RE = re.compile(
    r'^\s*([A-Z][A-Z0-9_]*):\s*"(.*)",\s*$', re.MULTILINE)
# Event argument of a record call: second positional argument.
RECORD_CALL_RE = re.compile(
    r"(?:\.record|_record_event)\(\s*([^,()]*|\([^()]*\)),\s*([^,)\s]+)")
BACKTICK_RE = re.compile(r"`([a-z][a-z0-9_]*)`")

README_BEGIN = "<!-- lint-events:begin -->"
README_END = "<!-- lint-events:end -->"


def vocabulary(events_py: Path) -> tuple[dict, dict]:
    """-> (constants {NAME: value} above EVENT_REGISTRY,
    registry {NAME: doc})."""
    text = events_py.read_text(encoding="utf-8")
    marker = text.find("EVENT_REGISTRY")
    if marker < 0:
        return {}, {}
    head = text[marker:]
    block = head[:head.find("\n}")]
    constants = {name: value for name, value
                 in CONSTANT_RE.findall(text[:marker])}
    registry = dict(REGISTRY_ENTRY_RE.findall(block))
    return constants, registry


def literal_record_sites(package: Path) -> list[str]:
    """Call sites passing the event as a raw string literal."""
    problems = []
    for path in sorted(package.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for _rid_arg, event_arg in RECORD_CALL_RE.findall(text):
            if event_arg.startswith(('"', "'")):
                problems.append(
                    f"{path.relative_to(package.parent)}: records "
                    f"event {event_arg} as a raw string literal (use "
                    f"a metrics/events.py constant so the registry "
                    f"and README stay load-bearing)")
    return problems


def readme_events(readme: Path) -> set[str]:
    """Backticked names inside the lint-events README section."""
    text = readme.read_text(encoding="utf-8")
    begin = text.find(README_BEGIN)
    end = text.find(README_END)
    if begin < 0 or end < 0:
        return set()
    return set(BACKTICK_RE.findall(text[begin:end]))


def main(argv: list[str]) -> int:
    repo = Path(__file__).resolve().parent.parent
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--package", type=Path,
                        default=repo / "vllm_distributed_tpu",
                        help="package tree to scan for record sites")
    parser.add_argument("--readme", type=Path,
                        default=repo / "README.md",
                        help="README carrying the events table")
    args = parser.parse_args(argv)
    events_py = args.package / "metrics" / "events.py"
    if not events_py.is_file():
        print(f"lint_events: no such file: {events_py}",
              file=sys.stderr)
        return 2
    if not args.readme.is_file():
        print(f"lint_events: no such file: {args.readme}",
              file=sys.stderr)
        return 2

    constants, registry = vocabulary(events_py)
    documented = readme_events(args.readme)
    problems: list[str] = []
    if not constants:
        problems.append("metrics/events.py: no event constants found "
                        "above EVENT_REGISTRY (parse drift?)")
    if not documented:
        problems.append(
            f"{args.readme.name}: no '{README_BEGIN}' section (the "
            f"events table must sit between the lint-events markers)")
    for name in sorted(set(constants) - set(registry)):
        problems.append(
            f"{name} (\"{constants[name]}\"): event constant missing "
            f"from EVENT_REGISTRY (add a one-line doc entry)")
    for name, doc in sorted(registry.items()):
        if not doc.strip():
            problems.append(f"{name}: EVENT_REGISTRY doc is empty")
    names = {constants[n] for n in constants if n in registry}
    for value in sorted(names - documented):
        if documented:
            problems.append(
                f"{value}: missing from the README events table "
                f"(between the lint-events markers)")
    for value in sorted(documented - set(constants.values())):
        problems.append(
            f"{value}: in the README events table but declared by no "
            f"event constant (orphaned row)")
    problems += literal_record_sites(args.package)
    if not problems:
        return 0
    print("vdt: event documentation drift:", file=sys.stderr)
    for p in problems:
        print(f"  {p}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
