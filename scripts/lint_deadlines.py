#!/usr/bin/env python3
"""Ban wall-clock deadline arithmetic in framework code.

``time.time()`` is an NTP-steppable clock: deadlines, TTLs, and timeout
windows computed from it mass-expire (or immortalize) when the host
clock steps — the regression class PR 1/2's monotonic migration removed.
This linter keeps it removed mechanically: every ``time.time()`` (and
``default_factory=time.time``) occurrence under ``vllm_distributed_tpu/``
must carry a ``wallclock-ok`` marker comment on its own line or the line
directly above, asserting it is a timestamp-only use (API ``created``
fields, stats epochs, informational heartbeat payloads) — never deadline
arithmetic.

Usage::

    python scripts/lint_deadlines.py [--root DIR]

Exit 0 when clean; exit 1 listing offending file:line pairs otherwise.
"""

import argparse
import re
import sys
from pathlib import Path

# What counts as a wall-clock read. Catches the call form and the
# dataclass default_factory reference (evaluated at instance creation).
WALLCLOCK_RE = re.compile(
    r"time\.time\(\)|default_factory\s*=\s*time\.time\b")
MARKER = "wallclock-ok"

DEFAULT_PACKAGE = "vllm_distributed_tpu"


def find_violations(root: Path) -> list[tuple[Path, int, str]]:
    violations: list[tuple[Path, int, str]] = []
    for path in sorted(root.rglob("*.py")):
        lines = path.read_text(encoding="utf-8").splitlines()
        for lineno, line in enumerate(lines, start=1):
            if not WALLCLOCK_RE.search(line):
                continue
            prev = lines[lineno - 2] if lineno >= 2 else ""
            if MARKER in line or MARKER in prev:
                continue
            violations.append((path, lineno, line.strip()))
    return violations


def main(argv: list[str]) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--root", type=Path,
        default=Path(__file__).resolve().parent.parent / DEFAULT_PACKAGE,
        help="directory tree to lint (default: the framework package)")
    args = parser.parse_args(argv)
    if not args.root.is_dir():
        print(f"lint_deadlines: no such directory: {args.root}",
              file=sys.stderr)
        return 2
    violations = find_violations(args.root)
    if not violations:
        return 0
    print("wall-clock reads without a 'wallclock-ok' marker (use "
          "time.monotonic() for deadlines/TTLs, or annotate "
          "timestamp-only uses):", file=sys.stderr)
    for path, lineno, line in violations:
        print(f"  {path}:{lineno}: {line}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
