#!/bin/bash
# Probe the (flaky) tunnelled TPU every few minutes; when it answers, run
# the FULL bench.py immediately (a tunnel window may be short) and write
# the TPU-backend JSON record to BENCH_tpu.json. Exits after the first
# successful TPU-backend bench record. Runs all round (~12 h of attempts).
cd /root/repo
LOG=tpu_bench_attempts.log
for i in $(seq 1 170); do
  echo "[watch] attempt $i $(date -u +%H:%M:%S)" >> "$LOG"
  timeout 180 python -c "
import jax, jax.numpy as jnp
d = jax.devices()
assert d[0].platform != 'cpu'
x = jnp.ones((512,512), jnp.bfloat16)
(x @ x).block_until_ready()
print('TPU_OK', d[0].device_kind)
" >> "$LOG" 2>&1
  if [ $? -eq 0 ]; then
    echo "[watch] probe ok; running full bench $(date -u +%H:%M:%S)" >> "$LOG"
    timeout 2400 python bench.py > bench_out_watch.json 2>bench_stderr_watch.log
    cat bench_out_watch.json >> "$LOG"
    if grep -q '"backend": "tpu"' bench_out_watch.json; then
      cp bench_out_watch.json BENCH_tpu.json
      echo "[watch] TPU bench captured -> BENCH_tpu.json" >> "$LOG"
      exit 0
    fi
    echo "[watch] bench did not produce tpu record; tail of stderr:" >> "$LOG"
    tail -3 bench_stderr_watch.log >> "$LOG"
  fi
  sleep 230
done
