#!/bin/bash
# Probe the (flaky) tunnelled TPU every few minutes; when it answers, run
# bench.py and append the JSON line to tpu_bench_attempts.log. Exits after
# the first successful TPU-backend bench record.
cd /root/repo
LOG=tpu_bench_attempts.log
for i in $(seq 1 60); do
  echo "[watch] attempt $i $(date -u +%H:%M:%S)" >> "$LOG"
  timeout 180 python -c "
import jax, jax.numpy as jnp
d = jax.devices()
assert d[0].platform != 'cpu'
x = jnp.ones((512,512), jnp.bfloat16)
(x @ x).block_until_ready()
print('TPU_OK', d[0].device_kind)
" >> "$LOG" 2>&1
  if [ $? -eq 0 ]; then
    echo "[watch] probe ok; running bench $(date -u +%H:%M:%S)" >> "$LOG"
    timeout 2400 python bench.py >> "$LOG" 2>bench_stderr_watch.log
    if grep -q '"backend": "tpu"' "$LOG"; then
      echo "[watch] TPU bench captured" >> "$LOG"
      exit 0
    fi
    echo "[watch] bench did not produce tpu record; tail of stderr:" >> "$LOG"
    tail -3 bench_stderr_watch.log >> "$LOG"
  fi
  sleep 240
done
