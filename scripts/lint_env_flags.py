#!/usr/bin/env python3
"""Ban undocumented (and orphaned) ``VDT_*`` environment flags.

Every flag registered in ``envs.py`` must have a row in the README's
environment-flag table, and every table row must name a registered
flag — otherwise operator-facing knobs ship silently (several PR 9-11
flags did) and the README rots. Mechanically:

* **registered** — every key of the ``environment_variables`` dict
  literal in ``envs.py`` (flags only ever enter the registry as
  string-literal keys), parsed textually so the linter runs without
  importing the package.
* **documented** — every README table row whose first cell is a
  backticked ``VDT_*`` token (``| `VDT_FOO` | ... |``). Prose mentions
  do not count: the table is the reference surface dashboards and
  operators read.

Failures: a registered flag without a table row, or a table row naming
a flag the registry does not know (orphaned row).

Usage::

    python scripts/lint_env_flags.py [--envs FILE] [--readme FILE]

Exit 0 when clean; exit 1 listing violations otherwise.
"""

import argparse
import re
import sys
from pathlib import Path

# One registry entry: an (indented) string-literal dict key.
REGISTRY_KEY_RE = re.compile(r'^\s*"(VDT_[A-Z0-9_]+)":', re.M)
# One README table row whose first cell is a backticked flag name.
README_ROW_RE = re.compile(r"^\|\s*`(VDT_[A-Z0-9_]+)`", re.M)


def registered_flags(envs_path: Path) -> set[str]:
    text = envs_path.read_text(encoding="utf-8")
    marker = text.find("environment_variables")
    if marker < 0:
        return set()
    # Scope to the registry dict literal so stray string keys elsewhere
    # in the module can't parse as flags.
    end = text.find("\n}", marker)
    block = text[marker:end if end > 0 else len(text)]
    return set(REGISTRY_KEY_RE.findall(block))


def documented_flags(readme_path: Path) -> set[str]:
    return set(README_ROW_RE.findall(
        readme_path.read_text(encoding="utf-8")))


def main(argv: list[str]) -> int:
    repo = Path(__file__).resolve().parent.parent
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--envs", type=Path,
                        default=repo / "vllm_distributed_tpu" / "envs.py",
                        help="environment-variable registry module")
    parser.add_argument("--readme", type=Path,
                        default=repo / "README.md",
                        help="README carrying the env-flag table")
    args = parser.parse_args(argv)
    if not args.envs.is_file():
        print(f"lint_env_flags: no such file: {args.envs}",
              file=sys.stderr)
        return 2
    if not args.readme.is_file():
        print(f"lint_env_flags: no such file: {args.readme}",
              file=sys.stderr)
        return 2

    registered = registered_flags(args.envs)
    documented = documented_flags(args.readme)
    problems: list[str] = []
    for name in sorted(registered - documented):
        problems.append(f"{name}: registered in envs.py but missing "
                        f"from the README env-flag table "
                        f"({args.readme.name})")
    for name in sorted(documented - registered):
        problems.append(f"{name}: in the README env-flag table but "
                        f"not registered in envs.py (orphaned row)")
    if not problems:
        return 0
    print("VDT_* env-flag documentation drift:", file=sys.stderr)
    for p in problems:
        print(f"  {p}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
