#!/usr/bin/env python3
"""Ban undocumented (and orphaned) ``vdt:`` metrics and label sets.

Every metric name the package emits must be (a) exposed with HELP/TYPE
lines and (b) listed in the README metrics table — otherwise dashboards
silently miss new families and the README rots. Mechanically:

* **emitted** — every quoted ``"vdt:..."`` string literal under the
  package tree (metric names only ever cross the code as literals).
* **has exposition** — the name appears in ``metrics/prometheus.py`` or
  ``metrics/stats.py`` (their render paths emit HELP/TYPE for every
  name they carry), or some package file contains a literal
  ``# HELP <name>`` (ad-hoc exposition blocks, e.g. the admission gauges
  in the API server).
* **documented** — the name appears in the README metrics table
  (any backticked ``vdt:...`` token in the README counts).
* **labels documented** — every family in the ``LABELED_METRICS``
  registry of ``metrics/prometheus.py`` (the single source of truth
  for label names) must appear in the README with its exact label set,
  as ``` `vdt:name{label1,label2}` ```; a README row carrying labels
  the registry does not declare is equally a failure.

Failures: emitted without exposition, emitted without a README row, a
README row naming a metric nothing emits (orphan), a labeled family
whose README row is missing its label set, or a README label set the
registry does not declare.

* **dynamic labels need a cardinality note** — label names whose
  values come from TRAFFIC rather than a fixed enum (today:
  ``tenant``) are a series-explosion hazard; every registry family
  carrying one must document, on its README table row, the mechanism
  that bounds the label space (for ``tenant``: the
  ``VDT_QOS_MAX_TRACKED_TENANTS`` hash/bucket cap). A row that names
  such a family without naming its bound fails.

Usage::

    python scripts/lint_metrics.py [--package DIR] [--readme FILE]

Exit 0 when clean; exit 1 listing violations otherwise.
"""

import argparse
import re
import sys
from pathlib import Path

METRIC_LITERAL_RE = re.compile(r"""["'](vdt:[a-z0-9_]+)""")
# Backticked README token, optionally carrying a {label1,label2} set.
METRIC_NAME_RE = re.compile(
    r"`(vdt:[a-z0-9_]+)(?:\{([a-z_][a-z_,]*)\})?")
# One LABELED_METRICS entry: "vdt:name": ("label", ...),
REGISTRY_ENTRY_RE = re.compile(
    r'"(vdt:[a-z0-9_]+)":\s*\(([^)]*)\)')
LABEL_NAME_RE = re.compile(r'"([a-z_]+)"')

# Modules whose registries/render helpers always emit HELP/TYPE for the
# names they carry.
EXPOSITION_MODULES = ("metrics/prometheus.py", "metrics/stats.py")

# Label names whose value space comes from traffic (not a fixed enum):
# a family carrying one must document its cardinality bound — the
# named token must appear on the metric's README table row.
DYNAMIC_LABELS = {"tenant": "VDT_QOS_MAX_TRACKED_TENANTS"}


def collect(package: Path) -> tuple[set, set]:
    """-> (emitted names, names with HELP/TYPE exposition)."""
    emitted: set[str] = set()
    exposed: set[str] = set()
    registry_text = ""
    for rel in EXPOSITION_MODULES:
        path = package / rel
        if path.is_file():
            registry_text += path.read_text(encoding="utf-8")
    for path in sorted(package.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for name in METRIC_LITERAL_RE.findall(text):
            emitted.add(name)
            if f"# HELP {name}" in text or name in registry_text:
                exposed.add(name)
    return emitted, exposed


def labeled_registry(package: Path) -> dict[str, frozenset]:
    """The LABELED_METRICS literal of metrics/prometheus.py, parsed
    textually (this linter runs without importing the package)."""
    path = package / "metrics" / "prometheus.py"
    if not path.is_file():
        return {}
    text = path.read_text(encoding="utf-8")
    marker = text.find("LABELED_METRICS")
    if marker < 0:
        return {}
    # Stop at the end of the dict literal so stray tuples elsewhere in
    # the module can't parse as registry entries.
    block = text[marker:text.find("}", marker)]
    return {
        name: frozenset(LABEL_NAME_RE.findall(labels))
        for name, labels in REGISTRY_ENTRY_RE.findall(block)
    }


def readme_metrics(readme: Path) -> dict[str, set]:
    """-> {name: set of documented label frozensets} (an unlabeled
    mention contributes an empty frozenset)."""
    out: dict[str, set] = {}
    for name, labels in METRIC_NAME_RE.findall(
            readme.read_text(encoding="utf-8")):
        sets = out.setdefault(name, set())
        sets.add(frozenset(labels.split(",")) if labels
                 else frozenset())
    return out


def main(argv: list[str]) -> int:
    repo = Path(__file__).resolve().parent.parent
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--package", type=Path,
                        default=repo / "vllm_distributed_tpu",
                        help="package tree to scan for emitted metrics")
    parser.add_argument("--readme", type=Path,
                        default=repo / "README.md",
                        help="README carrying the metrics table")
    args = parser.parse_args(argv)
    if not args.package.is_dir():
        print(f"lint_metrics: no such directory: {args.package}",
              file=sys.stderr)
        return 2
    if not args.readme.is_file():
        print(f"lint_metrics: no such file: {args.readme}",
              file=sys.stderr)
        return 2

    emitted, exposed = collect(args.package)
    documented = readme_metrics(args.readme)
    registry = labeled_registry(args.package)
    problems: list[str] = []
    for name in sorted(emitted - exposed):
        problems.append(f"{name}: emitted without HELP/TYPE exposition "
                        f"(add it to metrics/prometheus.py or an "
                        f"explicit '# HELP {name}' block)")
    for name in sorted(emitted - documented.keys()):
        problems.append(f"{name}: missing from the README metrics table "
                        f"({args.readme.name})")
    for name in sorted(documented.keys() - emitted):
        problems.append(f"{name}: in the README metrics table but "
                        f"emitted nowhere (orphaned row)")
    # Labeled families: the registry's label set must appear verbatim
    # in the README, and the README must not invent label sets.
    for name in sorted(registry):
        labels = registry[name]
        if not labels or name not in documented:
            continue  # missing row already reported above
        if labels not in documented[name]:
            want = ",".join(sorted(labels))
            problems.append(
                f"{name}: emitted with labels {{{want}}} but the "
                f"README row does not document them (write "
                f"`{name}{{{want}}}` in the metrics table)")
    for name in sorted(documented):
        declared = registry.get(name, frozenset())
        for labels in documented[name]:
            if labels and labels != declared:
                got = ",".join(sorted(labels))
                problems.append(
                    f"{name}: README documents labels {{{got}}} but "
                    f"the LABELED_METRICS registry declares "
                    f"{sorted(declared) if declared else 'none'}")
    # Dynamic (traffic-valued) labels: the family's README table row
    # must name the mechanism bounding the label space.
    readme_lines = args.readme.read_text(encoding="utf-8").splitlines()
    for name in sorted(registry):
        bounds = sorted({DYNAMIC_LABELS[lb] for lb in registry[name]
                         if lb in DYNAMIC_LABELS})
        if not bounds or name not in documented:
            continue
        rows = [ln for ln in readme_lines if f"`{name}{{" in ln]
        for bound in bounds:
            if rows and not any(bound in ln for ln in rows):
                problems.append(
                    f"{name}: carries a dynamic label but its README "
                    f"row has no cardinality note (mention `{bound}`, "
                    f"the bucketing bound, on the row)")
    if not problems:
        return 0
    print("vdt: metric documentation drift:", file=sys.stderr)
    for p in problems:
        print(f"  {p}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
