#!/usr/bin/env python3
"""Ban fault points no test exercises.

Every name in ``FAULT_POINTS`` (utils/fault_injection.py) is a
contract: some production code path consults it, and some drill proves
the degradation it triggers stays on its recovery ladder. A point that
no test references is an untested failure mode wearing a tested one's
uniform — the injection site can rot (or the recovery path regress)
with tier-1 staying green. Mechanically:

* **registered** — every string literal inside the ``FAULT_POINTS``
  tuple, parsed textually so the linter runs without importing the
  package (same approach as lint_env_flags.py's registry parse).
* **exercised** — the point's name appears as a string literal in at
  least one file under ``tests/``. A grep is deliberately the bar:
  drills arm points via ``inject("<name>", ...)`` / ``fire_or_raise``
  assertions / counters() lookups, all of which carry the literal.

Failures: a registered point with zero test-file references, or a
test referencing a point the registry does not know (typo'd drill —
``inject`` on an unregistered name raises at runtime, but only if that
test actually runs; the linter catches it statically).

Usage::

    python scripts/lint_faults.py [--registry FILE] [--tests DIR]

Exit 0 when clean; exit 1 listing violations otherwise.
"""

import argparse
import re
import sys
from pathlib import Path

# One registered point: a string literal inside the FAULT_POINTS tuple.
POINT_RE = re.compile(r'"([a-z0-9_]+\.[a-z0-9_]+)"')


def registered_points(registry_path: Path) -> set[str]:
    text = registry_path.read_text(encoding="utf-8")
    marker = text.find("FAULT_POINTS")
    if marker < 0:
        return set()
    start = text.find("(", marker)
    end = text.find(")", start)
    if start < 0 or end < 0:
        return set()
    return set(POINT_RE.findall(text[start:end]))


def test_references(tests_dir: Path,
                    points: set[str]) -> dict[str, list[str]]:
    """Map each point name to the test files whose text contains it
    as a quoted literal (single- or double-quoted)."""
    refs: dict[str, list[str]] = {p: [] for p in points}
    for path in sorted(tests_dir.rglob("*.py")):
        text = path.read_text(encoding="utf-8", errors="replace")
        for point in points:
            if f'"{point}"' in text or f"'{point}'" in text:
                refs[point].append(str(path.relative_to(tests_dir)))
    return refs


def unknown_references(tests_dir: Path,
                       points: set[str]) -> list[tuple[str, str]]:
    """(file, name) pairs for quoted dotted names passed to the
    injection API that are NOT registered points."""
    arm_re = re.compile(
        r'(?:inject|fire_or_raise|should_fire|maybe_delay)\(\s*'
        r'["\']([a-z0-9_]+\.[a-z0-9_]+)["\']')
    unknown: list[tuple[str, str]] = []
    for path in sorted(tests_dir.rglob("*.py")):
        text = path.read_text(encoding="utf-8", errors="replace")
        for name in arm_re.findall(text):
            if name not in points:
                unknown.append((str(path.relative_to(tests_dir)), name))
    return unknown


def main(argv: list[str]) -> int:
    repo = Path(__file__).resolve().parent.parent
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--registry", type=Path,
        default=(repo / "vllm_distributed_tpu" / "utils" /
                 "fault_injection.py"),
        help="module carrying the FAULT_POINTS tuple")
    parser.add_argument("--tests", type=Path, default=repo / "tests",
                        help="test tree to grep for point references")
    args = parser.parse_args(argv)
    if not args.registry.is_file():
        print(f"lint_faults: no such file: {args.registry}",
              file=sys.stderr)
        return 2
    if not args.tests.is_dir():
        print(f"lint_faults: no such directory: {args.tests}",
              file=sys.stderr)
        return 2

    points = registered_points(args.registry)
    if not points:
        print("lint_faults: could not parse FAULT_POINTS "
              f"from {args.registry}", file=sys.stderr)
        return 2
    refs = test_references(args.tests, points)
    problems: list[str] = []
    for point in sorted(points):
        if not refs[point]:
            problems.append(
                f"{point}: registered in FAULT_POINTS but exercised by "
                f"no file under {args.tests.name}/ (untested failure "
                f"mode)")
    for rel, name in unknown_references(args.tests, points):
        problems.append(
            f"{name}: armed by {args.tests.name}/{rel} but not in "
            f"FAULT_POINTS (typo'd drill)")
    if not problems:
        return 0
    print("Fault-point drill coverage drift:", file=sys.stderr)
    for p in problems:
        print(f"  {p}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
