#!/usr/bin/env python3
"""Validate BENCH_*.json records against the bench schema.

The BENCH files are the project's scoreboard: ROADMAP item 5 compares
real-TPU captures against them, and the perf-attribution plane (ISSUE
14) makes CPU-smoke and TPU records directly comparable ONLY if every
record keeps the machine-readable fields. This linter fails tier-1 when
a record drifts:

* **Every record** is a single JSON object with ``metric`` (str),
  ``value`` (finite number >= 0), ``unit`` (non-empty str) and
  ``backend`` in {tpu, cpu-fallback, cpu}.
* **tpu-backend records** must carry a numeric ``decode_mfu`` in
  (0, 1] — the scoreboard's roofline axis.
* **schema_version >= 2 records** (everything bench.py writes since
  the perf-attribution plane) must additionally carry ``decode_mbu``
  on tpu-backend records (decode is bandwidth-bound; MBU is the honest
  headline) and engine-sourced ``engine_mfu``/``engine_mbu`` on every
  backend (analytic fallback values count — the keys must exist and be
  numeric). Records WITHOUT ``schema_version`` are grandfathered
  pre-plane captures and validate against the v1 rules only.
* **schema_version >= 3 records** (the hierarchical KV-memory plane)
  must additionally carry the ``_tiering_leg`` comparison — turns/s
  and window hit rate for both legs plus the greedy-parity flag — or
  an explicit ``tiering_leg_error`` string recording why the leg
  could not run. A parity field that is present must be ``true``:
  tiering is contractually token-invisible.
* **schema_version >= 4 records** (the elastic fleet) must carry the
  ``_fleet_leg`` comparison — request throughput and peak-phase
  p50/p99 for both legs, the scale counters, the replica timeline and
  the greedy-parity flag — or an explicit ``fleet_leg_error`` string.
  ``fleet_parity`` must be ``true``: elasticity is contractually
  token-invisible, migrations included.
* **schema_version >= 5 records** (the HA fleet control plane) must
  carry the ``_ha_leg`` failover drill — leader transitions, the
  fenced-action counters, the observed failover gap, journal replays,
  the replica timeline and the greedy-parity flag — or an explicit
  ``ha_leg_error`` string. ``ha_parity`` must be ``true``: leader
  failover is contractually token-invisible, journal replays included.
* **schema_version >= 6 records** (the distributed trace plane) must
  carry the ``_trace_leg`` acceptance — ``trace_overhead_frac`` <= 3%
  (VDT_TRACE_PLANE on vs off), at least one stitched two-replica
  disagg trace and at least one Perfetto flow link across the KV
  handoff — or an explicit ``trace_leg_error`` string.
* **schema_version >= 7 records** (the correctness sentinel) must
  carry the ``_canary_leg`` acceptance — a clean soak of >= 60 canary
  probes with ZERO false positives, the seeded single-replica
  corruption detected within 3 probes with vote attribution and a
  quarantine hint, a bounded plane-on overhead fraction and greedy
  token parity — or an explicit ``canary_leg_error`` string.

Usage::

    python scripts/lint_bench.py [--dir REPO_ROOT]

Exit 0 when clean; exit 1 listing violations otherwise.
"""

import argparse
import glob
import json
import math
import os
import sys

BACKENDS = {"tpu", "cpu-fallback", "cpu"}
REQUIRED = ("metric", "value", "unit", "backend")


def _is_num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool) \
        and math.isfinite(x)


def check_record(name: str, rec) -> list:
    errs = []
    if not isinstance(rec, dict):
        return [f"{name}: not a JSON object"]
    for key in REQUIRED:
        if key not in rec:
            errs.append(f"{name}: missing required field {key!r}")
    if "metric" in rec and not (isinstance(rec["metric"], str)
                                and rec["metric"]):
        errs.append(f"{name}: metric must be a non-empty string")
    if "value" in rec and not (_is_num(rec["value"])
                               and rec["value"] >= 0):
        errs.append(f"{name}: value must be a finite number >= 0")
    if "unit" in rec and not (isinstance(rec["unit"], str)
                              and rec["unit"]):
        errs.append(f"{name}: unit must be a non-empty string")
    backend = rec.get("backend")
    if backend is not None and backend not in BACKENDS:
        errs.append(f"{name}: backend {backend!r} not in "
                    f"{sorted(BACKENDS)}")
    is_tpu = backend == "tpu"
    if is_tpu:
        mfu = rec.get("decode_mfu")
        if not (_is_num(mfu) and 0 < mfu <= 1):
            errs.append(f"{name}: tpu record needs decode_mfu in "
                        f"(0, 1], got {mfu!r}")
    version = rec.get("schema_version")
    if version is not None:
        if not (_is_num(version) and version >= 1):
            errs.append(f"{name}: schema_version must be a number >= 1")
            return errs
        if version >= 2:
            if is_tpu and not _is_num(rec.get("decode_mbu")):
                errs.append(f"{name}: schema>=2 tpu record needs a "
                            "numeric decode_mbu next to decode_mfu")
            for key in ("engine_mfu", "engine_mbu"):
                if not _is_num(rec.get(key)):
                    errs.append(
                        f"{name}: schema>=2 record needs engine-"
                        f"sourced {key} (analytic fallback counts), "
                        f"got {rec.get(key)!r}")
        if version >= 3:
            errs.extend(_check_tiering_fields(name, rec))
        if version >= 4:
            errs.extend(_check_fleet_fields(name, rec))
        if version >= 5:
            errs.extend(_check_ha_fields(name, rec))
        if version >= 6:
            errs.extend(_check_trace_fields(name, rec))
        if version >= 7:
            errs.extend(_check_canary_fields(name, rec))
    return errs


# _tiering_leg comparison fields required on schema >= 3 records
# ((validator, description) per field; see bench.py _tiering_leg).
TIERING_FIELDS = {
    "tiering_on_turns_per_s": (
        lambda v: _is_num(v) and v > 0, "positive number"),
    "tiering_off_turns_per_s": (
        lambda v: _is_num(v) and v > 0, "positive number"),
    "tiering_on_hit_rate_window": (
        lambda v: _is_num(v) and 0 <= v <= 1, "number in [0, 1]"),
    "tiering_off_hit_rate_window": (
        lambda v: _is_num(v) and 0 <= v <= 1, "number in [0, 1]"),
    "tiering_parity": (lambda v: v is True,
                       "true (tiering must be token-invisible)"),
}


def _check_tiering_fields(name: str, rec: dict) -> list:
    err = rec.get("tiering_leg_error")
    if err is not None:
        if isinstance(err, str) and err:
            return []  # leg failed and says why — valid record
        return [f"{name}: tiering_leg_error must be a non-empty "
                f"string, got {err!r}"]
    errs = []
    for key, (ok, want) in TIERING_FIELDS.items():
        if not ok(rec.get(key)):
            errs.append(f"{name}: schema>=3 record needs {key} "
                        f"({want}), got {rec.get(key)!r}")
    return errs


# _fleet_leg comparison fields required on schema >= 4 records
# ((validator, description) per field; see bench.py _fleet_leg).
FLEET_FIELDS = {
    "fleet_on_reqs_per_s": (
        lambda v: _is_num(v) and v > 0, "positive number"),
    "fleet_off_reqs_per_s": (
        lambda v: _is_num(v) and v > 0, "positive number"),
    "fleet_on_req_p99_ms": (
        lambda v: _is_num(v) and v > 0, "positive number"),
    "fleet_off_req_p99_ms": (
        lambda v: _is_num(v) and v > 0, "positive number"),
    "fleet_scale_outs": (
        lambda v: _is_num(v) and v >= 0, "number >= 0"),
    "fleet_scale_ins": (
        lambda v: _is_num(v) and v >= 0, "number >= 0"),
    "fleet_replica_timeline": (
        lambda v: (isinstance(v, list) and v
                   and all(_is_num(x) and x >= 1 for x in v)),
        "non-empty list of replica counts >= 1"),
    "fleet_parity": (lambda v: v is True,
                     "true (elasticity must be token-invisible)"),
}


def _check_fleet_fields(name: str, rec: dict) -> list:
    err = rec.get("fleet_leg_error")
    if err is not None:
        if isinstance(err, str) and err:
            return []  # leg failed and says why — valid record
        return [f"{name}: fleet_leg_error must be a non-empty "
                f"string, got {err!r}"]
    errs = []
    for key, (ok, want) in FLEET_FIELDS.items():
        if not ok(rec.get(key)):
            errs.append(f"{name}: schema>=4 record needs {key} "
                        f"({want}), got {rec.get(key)!r}")
    return errs


# _ha_leg failover-drill fields required on schema >= 5 records
# ((validator, description) per field; see bench.py _ha_leg).
HA_FIELDS = {
    "ha_leader_transitions": (
        lambda v: _is_num(v) and v >= 2,
        "number >= 2 (election + the failover takeover)"),
    "ha_failover_gap_s": (
        lambda v: _is_num(v) and v >= 0, "number >= 0"),
    "ha_journal_replays": (
        lambda v: _is_num(v) and v >= 1,
        "number >= 1 (the successor replayed the mid-drain intent)"),
    "ha_fenced_actions": (
        lambda v: (isinstance(v, dict)
                   and all(isinstance(k, str) and _is_num(n) and n >= 0
                           for k, n in v.items())),
        "dict of action -> rejection count"),
    "ha_replica_timeline": (
        lambda v: (isinstance(v, list) and v
                   and all(_is_num(x) and x >= 1 for x in v)),
        "non-empty list of replica counts >= 1"),
    "ha_parity": (lambda v: v is True,
                  "true (leader failover must be token-invisible)"),
}


def _check_ha_fields(name: str, rec: dict) -> list:
    err = rec.get("ha_leg_error")
    if err is not None:
        if isinstance(err, str) and err:
            return []  # leg failed and says why — valid record
        return [f"{name}: ha_leg_error must be a non-empty "
                f"string, got {err!r}"]
    errs = []
    for key, (ok, want) in HA_FIELDS.items():
        if not ok(rec.get(key)):
            errs.append(f"{name}: schema>=5 record needs {key} "
                        f"({want}), got {rec.get(key)!r}")
    return errs


# _trace_leg acceptance fields required on schema >= 6 records
# ((validator, description) per field; see bench.py _trace_leg).
TRACE_FIELDS = {
    "trace_overhead_frac": (
        lambda v: _is_num(v) and v <= 0.03,
        "number <= 0.03 (the trace plane may cost at most 3%)"),
    "trace_stitched_traces": (
        lambda v: _is_num(v) and v >= 1,
        "number >= 1 (a disagg request must stitch both replicas "
        "into one trace)"),
    "trace_flow_links": (
        lambda v: _is_num(v) and v >= 1,
        "number >= 1 (the KV handoff must carry a Perfetto s/f "
        "flow pair)"),
}


def _check_trace_fields(name: str, rec: dict) -> list:
    err = rec.get("trace_leg_error")
    if err is not None:
        if isinstance(err, str) and err:
            return []  # leg failed and says why — valid record
        return [f"{name}: trace_leg_error must be a non-empty "
                f"string, got {err!r}"]
    errs = []
    for key, (ok, want) in TRACE_FIELDS.items():
        if not ok(rec.get(key)):
            errs.append(f"{name}: schema>=6 record needs {key} "
                        f"({want}), got {rec.get(key)!r}")
    return errs


# _canary_leg acceptance fields required on schema >= 7 records
# ((validator, description) per field; see bench.py _canary_leg).
CANARY_FIELDS = {
    "canary_soak_probes": (
        lambda v: _is_num(v) and v >= 60,
        "number >= 60 (the clean soak must be long enough to trust "
        "the zero-false-positive claim)"),
    "canary_false_positives": (
        lambda v: v == 0 and not isinstance(v, bool),
        "exactly 0 (a sentinel that cries wolf gets ignored)"),
    "canary_detection_probes": (
        lambda v: _is_num(v) and 1 <= v <= 3,
        "number in [1, 3] (the seeded corruption must be caught "
        "within 3 probes)"),
    "canary_vote_attribution": (
        lambda v: v is True,
        "true (the vote must isolate exactly the corrupted replica)"),
    "canary_quarantine_hint": (
        lambda v: v is True,
        "true (sustained divergence must emit a quarantine hint)"),
    "canary_overhead_frac": (
        lambda v: _is_num(v) and v <= 0.05,
        "number <= 0.05 (the always-on numerics tap may cost at "
        "most 5%)"),
    "canary_parity": (
        lambda v: v is True,
        "true (the sentinel must be invisible to tenant tokens)"),
}


def _check_canary_fields(name: str, rec: dict) -> list:
    err = rec.get("canary_leg_error")
    if err is not None:
        if isinstance(err, str) and err:
            return []  # leg failed and says why — valid record
        return [f"{name}: canary_leg_error must be a non-empty "
                f"string, got {err!r}"]
    errs = []
    for key, (ok, want) in CANARY_FIELDS.items():
        if not ok(rec.get(key)):
            errs.append(f"{name}: schema>=7 record needs {key} "
                        f"({want}), got {rec.get(key)!r}")
    return errs


def main(argv) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dir", default=repo,
                        help="directory holding BENCH_*.json")
    args = parser.parse_args(argv)
    paths = sorted(glob.glob(os.path.join(args.dir, "BENCH_*.json")))
    errors: list = []
    for path in paths:
        name = os.path.basename(path)
        try:
            with open(path, encoding="utf-8") as f:
                rec = json.load(f)
        except Exception as e:  # noqa: BLE001 - that IS the finding
            errors.append(f"{name}: unparseable JSON ({e})")
            continue
        if isinstance(rec, dict) and "parsed" in rec:
            # Driver wrapper shape: {"n", "cmd", "rc", "tail",
            # "parsed": <bench record>}. A failed capture (rc != 0)
            # legitimately carries parsed=null — the schema binds the
            # RECORD, not the driver's failure bookkeeping.
            payload = rec.get("parsed")
            if payload is None:
                if rec.get("rc", 1) == 0:
                    errors.append(
                        f"{name}: rc=0 wrapper with no parsed record")
                continue
            rec = payload
        errors.extend(check_record(name, rec))
    if errors:
        print(f"lint_bench: {len(errors)} violation(s) in "
              f"{len(paths)} record(s):", file=sys.stderr)
        for e in errors:
            print(f"  {e}", file=sys.stderr)
        return 1
    print(f"lint_bench: {len(paths)} record(s) clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
